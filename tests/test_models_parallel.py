"""Models / ops / parallel tests on the virtual 8-device CPU mesh."""

from functools import partial

import numpy as np
import pytest


@pytest.fixture(scope="module")
def mesh8(cpu_mesh_devices):
    import jax
    from raydp_tpu.parallel import make_mesh

    return make_mesh({"sp": 8}, jax.devices()[:8])


def test_ring_attention_matches_full(mesh8):
    import jax.numpy as jnp
    from raydp_tpu.parallel import full_attention, ring_attention_sharded

    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, 4, 64, 8)), jnp.float32)
        for _ in range(3)
    )
    for causal in (False, True):
        ref = full_attention(q, k, v, causal=causal)
        out = ring_attention_sharded(q, k, v, mesh8, axis="sp", causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_with_flash_blocks(mesh8):
    """Ring attention computing each block product with the fused pallas
    flash kernel (ROADMAP item 2): exact vs full attention."""
    import jax.numpy as jnp

    from raydp_tpu.parallel import full_attention, ring_attention_sharded

    rng = np.random.default_rng(14)
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, 4, 256, 16)), jnp.float32)
        for _ in range(3)
    )
    for causal in (False, True):
        ref = full_attention(q, k, v, causal=causal)
        out = ring_attention_sharded(
            q, k, v, mesh8, axis="sp", causal=causal, use_flash=True
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=5e-5)


def test_ulysses_attention_matches_full(mesh8):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from raydp_tpu.parallel import full_attention, ulysses_attention

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    rng = np.random.default_rng(1)
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, 8, 64, 8)), jnp.float32)
        for _ in range(3)
    )
    spec = P(None, None, "sp", None)
    out = shard_map(
        partial(ulysses_attention, axis_name="sp", causal=True),
        mesh=mesh8, in_specs=(spec,) * 3, out_specs=spec,
    )(q, k, v)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_dot_interaction_pallas_matches_xla():
    import jax.numpy as jnp
    from raydp_tpu.ops import dot_interaction, dot_interaction_pallas

    rng = np.random.default_rng(2)
    stacked = jnp.asarray(rng.standard_normal((36, 9, 16)), jnp.float32)
    ref = dot_interaction(stacked)
    assert ref.shape == (36, 36)  # 9*8/2
    out = dot_interaction_pallas(stacked, block_batch=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_flash_attention_matches_reference():
    import jax
    import jax.numpy as jnp

    from raydp_tpu.ops import flash_attention
    from raydp_tpu.ops.flash_attention import _reference

    rng = np.random.default_rng(7)
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, 4, 128, 32)), jnp.float32)
        for _ in range(3)
    )
    for causal in (False, True):
        out = flash_attention(q, k, v, causal, 64, 64)
        ref = _reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    # gradients flow through the custom VJP
    grad = jax.grad(lambda q_: jnp.sum(flash_attention(q_, k, v, True, 64, 64) ** 2))(q)
    ref_grad = jax.grad(lambda q_: jnp.sum(_reference(q_, k, v, True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_grad), atol=5e-4)


def test_flash_attention_backward_blockwise_exact():
    """The pallas backward (dq/dk/dv from saved o + logsumexp — no [T,T]
    matrix) must match gradients through the exact reference for every input,
    both maskings, and blocks that straddle the causal diagonal."""
    import jax
    import jax.numpy as jnp

    from raydp_tpu.ops import flash_attention
    from raydp_tpu.ops.flash_attention import _reference

    rng = np.random.default_rng(13)
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, 3, 256, 32)), jnp.float32)
        for _ in range(3)
    )
    g = jnp.asarray(rng.standard_normal((2, 3, 256, 32)), jnp.float32)

    for causal in (False, True):
        for bq, bk in ((64, 64), (128, 32)):
            _, vjp = jax.vjp(
                lambda q_, k_, v_: flash_attention(q_, k_, v_, causal, bq, bk),
                q, k, v,
            )
            dq, dk, dv = vjp(g)
            _, ref_vjp = jax.vjp(
                lambda q_, k_, v_: _reference(q_, k_, v_, causal), q, k, v
            )
            rdq, rdk, rdv = ref_vjp(g)
            np.testing.assert_allclose(np.asarray(dq), np.asarray(rdq), atol=1e-4)
            np.testing.assert_allclose(np.asarray(dk), np.asarray(rdk), atol=1e-4)
            np.testing.assert_allclose(np.asarray(dv), np.asarray(rdv), atol=1e-4)


def test_flash_attention_training_memory_is_linear():
    """Jaxpr-level check that the backward never materializes a [T, T]
    score matrix: the largest intermediate in the VJP scales with T, not T²
    (the round-1 backward recomputed through full attention and OOMed at
    the lengths the forward could handle)."""
    import jax
    import jax.numpy as jnp

    from raydp_tpu.ops import flash_attention

    t = 2048
    q = jax.ShapeDtypeStruct((1, 1, t, 32), jnp.float32)

    def loss(q_, k_, v_):
        return jnp.sum(flash_attention(q_, k_, v_, True, 128, 128) ** 2)

    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, q, q)

    def subjaxprs(eqn):
        for val in eqn.params.values():
            for v in val if isinstance(val, (list, tuple)) else [val]:
                if hasattr(v, "jaxpr"):
                    yield v.jaxpr
                elif hasattr(v, "eqns"):
                    yield v

    def max_elems(jpr):
        worst = 0
        for eqn in jpr.eqns:
            for var in eqn.outvars:
                shape = getattr(var.aval, "shape", ())
                n = int(np.prod(shape)) if shape else 1
                worst = max(worst, n)
            for sub in subjaxprs(eqn):
                worst = max(worst, max_elems(sub))
        return worst

    largest = max_elems(jaxpr.jaxpr)
    # O(T): q itself is t*32 elems; a [T,T] matrix would be t*t = 64x larger
    assert largest <= t * 32 * 4, (
        f"backward materializes an intermediate of {largest} elements "
        f"(≥ [T,T] = {t*t})"
    )


def test_transformer_flash_matches_full():
    import jax
    import jax.numpy as jnp

    from raydp_tpu.models import TransformerLM

    tokens = jnp.asarray(
        np.random.default_rng(8).integers(0, 50, size=(2, 128)), jnp.int32
    )
    full = TransformerLM(
        vocab_size=50, d_model=32, num_heads=4, num_layers=2, max_len=128,
        attn_impl="full", dtype=jnp.float32,
    )
    params = full.init(jax.random.PRNGKey(0), tokens)
    import dataclasses

    flash = dataclasses.replace(full, attn_impl="flash")
    np.testing.assert_allclose(
        np.asarray(flash.apply(params, tokens)),
        np.asarray(full.apply(params, tokens)),
        atol=2e-3,
    )


def test_sharded_embedding_lookup(cpu_mesh_devices):
    import jax
    import jax.numpy as jnp
    from raydp_tpu.ops import sharded_embedding_lookup
    from raydp_tpu.parallel import make_mesh

    mesh = make_mesh({"model": 8}, jax.devices()[:8])
    rng = np.random.default_rng(3)
    table = jnp.asarray(rng.standard_normal((64, 8)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, 64, size=(4, 5)), jnp.int32)
    out = sharded_embedding_lookup(table, ids, mesh, axis="model")
    ref = jnp.take(table, ids, axis=0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_dlrm_forward_and_sharded_tables(cpu_mesh_devices):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from raydp_tpu.models import DLRM, dlrm_sharding_rules
    from raydp_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 4, "model": 2}, jax.devices()[:8])
    vocab_sizes = [32, 64, 16]
    model = DLRM(vocab_sizes=vocab_sizes, num_dense=4, embed_dim=8)
    rng = np.random.default_rng(4)
    dense = rng.random((16, 4)).astype(np.float32)
    ids = rng.integers(0, 16, size=(16, 3)).astype(np.float32)
    x = jnp.asarray(np.concatenate([dense, ids], axis=1))
    params = model.init(jax.random.PRNGKey(0), x)

    shardings = dlrm_sharding_rules()(mesh, params)
    params_sharded = jax.device_put(params, shardings)
    # table actually sharded over model axis
    table = params_sharded["params"]["embedding_0"]
    assert table.sharding.spec == P("model", None)

    with mesh:
        out = jax.jit(model.apply)(params_sharded, x)
    assert out.shape == (16, 1)
    ref = model.apply(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.slow
def test_transformer_ring_matches_full(mesh8):
    import jax
    import jax.numpy as jnp

    from raydp_tpu.models import TransformerLM, sequence_parallel_apply

    rng = np.random.default_rng(5)
    tokens = jnp.asarray(rng.integers(0, 50, size=(2, 64)), jnp.int32)
    full = TransformerLM(
        vocab_size=50, d_model=32, num_heads=8, num_layers=2, max_len=64,
        attn_impl="full", dtype=jnp.float32,
    )
    params = full.init(jax.random.PRNGKey(0), tokens)
    ref = full.apply(params, tokens)

    ring = TransformerLM(
        vocab_size=50, d_model=32, num_heads=8, num_layers=2, max_len=64,
        attn_impl="ring", dtype=jnp.float32,
    )
    out = sequence_parallel_apply(ring, params, tokens, mesh8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_transformer_train_step_sp(mesh8):
    """One optimization step with sequence parallelism: loss finite, grads flow."""
    import jax
    import jax.numpy as jnp
    import optax

    from raydp_tpu.models import TransformerLM, sequence_parallel_apply

    model = TransformerLM(
        vocab_size=50, d_model=32, num_heads=8, num_layers=1, max_len=64,
        attn_impl="ring", dtype=jnp.float32,
    )
    tokens = jnp.asarray(
        np.random.default_rng(6).integers(0, 50, size=(2, 64)), jnp.int32
    )
    # init outside shard_map needs an axis-free twin (same param structure)
    import dataclasses

    init_model = dataclasses.replace(model, attn_impl="full")
    params = init_model.init(jax.random.PRNGKey(0), tokens[:, :8])
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, tokens):
        def loss_fn(p):
            logits = sequence_parallel_apply(model, p, tokens[:, :-1], mesh8)
            targets = tokens[:, 1:]
            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(logits, targets)
            )

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # 64-1 = 63 tokens does not divide 8 — pad to 64 with a wrap token
    padded = jnp.concatenate([tokens, tokens[:, :1]], axis=1)
    params, opt_state, loss = step(params, opt_state, padded)
    assert np.isfinite(float(loss))


def test_pipeline_parallel_matches_sequential(cpu_mesh_devices):
    import jax
    import jax.numpy as jnp

    from raydp_tpu.parallel import make_mesh, pipeline_sharded

    mesh = make_mesh({"pp": 4}, jax.devices()[:4])
    rng = np.random.default_rng(9)
    D = 16
    Ws = jnp.asarray(rng.standard_normal((4, D, D)) * 0.3, jnp.float32)
    x = jnp.asarray(rng.standard_normal((32, D)), jnp.float32)

    def stage_fn(W, t):
        return jax.nn.relu(t @ W)

    ref = x
    for i in range(4):
        ref = stage_fn(Ws[i], ref)
    out = pipeline_sharded(stage_fn, Ws, x, mesh, num_microbatches=8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    grad = jax.grad(
        lambda w: jnp.sum(pipeline_sharded(stage_fn, w, x, mesh, 8) ** 2)
    )(Ws)

    def seq_loss(w):
        y = x
        for i in range(4):
            y = stage_fn(w[i], y)
        return jnp.sum(y**2)

    ref_grad = jax.grad(seq_loss)(Ws)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_grad), atol=1e-4)


def test_moe_expert_parallel_matches_dense(cpu_mesh_devices):
    import jax
    import jax.numpy as jnp

    from raydp_tpu.parallel import make_mesh, moe_sharded

    N, D, B = 4, 8, 64
    mesh = make_mesh({"ep": N}, jax.devices()[:N])
    rng = np.random.default_rng(10)
    Ws = jnp.asarray(rng.standard_normal((N, D, D)) * 0.5, jnp.float32)
    Wr = jnp.asarray(rng.standard_normal((D, N)) * 0.5, jnp.float32)
    x = jnp.asarray(rng.standard_normal((B, D)), jnp.float32)

    def expert_fn(W, t):
        return jax.nn.relu(t @ W)

    gates = jax.nn.softmax(x @ Wr, -1)
    assign = jnp.argmax(gates, -1)
    gate = jnp.take_along_axis(gates, assign[:, None], 1)[:, 0]
    dense = jnp.stack([expert_fn(Ws[e], x) for e in range(N)], 1)
    ref = dense[jnp.arange(B), assign] * gate[:, None]

    out = moe_sharded(expert_fn, Ws, Wr, x, mesh, capacity_factor=8.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    # gradients through the double all_to_all + dispatch einsums
    grad = jax.grad(
        lambda w: jnp.sum(moe_sharded(expert_fn, w, Wr, x, mesh, capacity_factor=8.0) ** 2)
    )(Ws)

    def dense_loss(w):
        d = jnp.stack([expert_fn(w[e], x) for e in range(N)], 1)
        return jnp.sum((d[jnp.arange(B), assign] * gate[:, None]) ** 2)

    ref_grad = jax.grad(dense_loss)(Ws)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_grad), atol=1e-4)


def test_moe_top2_matches_dense(cpu_mesh_devices):
    """Top-2 routing with renormalized gates must equal the dense two-expert
    mixture when capacity is ample, and expose aux stats."""
    import jax
    import jax.numpy as jnp

    from raydp_tpu.parallel import make_mesh, moe_sharded

    N, D, B = 4, 8, 64
    mesh = make_mesh({"ep": N}, jax.devices()[:N])
    rng = np.random.default_rng(21)
    Ws = jnp.asarray(rng.standard_normal((N, D, D)) * 0.5, jnp.float32)
    Wr = jnp.asarray(rng.standard_normal((D, N)) * 0.5, jnp.float32)
    x = jnp.asarray(rng.standard_normal((B, D)), jnp.float32)

    def expert_fn(W, t):
        return jax.nn.relu(t @ W)

    gates = jax.nn.softmax(x @ Wr, -1)
    top_vals, top_idx = jax.lax.top_k(gates, 2)
    w = top_vals / jnp.sum(top_vals, -1, keepdims=True)
    dense = jnp.stack([expert_fn(Ws[e], x) for e in range(N)], 1)  # [B,N,D]
    ref = (
        dense[jnp.arange(B), top_idx[:, 0]] * w[:, :1]
        + dense[jnp.arange(B), top_idx[:, 1]] * w[:, 1:]
    )

    out, aux = moe_sharded(
        expert_fn, Ws, Wr, x, mesh, capacity_factor=8.0, top_k=2,
        return_aux=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    assert float(aux["drop_fraction"]) == 0.0  # ample capacity
    assert float(aux["load_balance_loss"]) >= 1.0  # ==1 only at perfect balance

    # gradients flow through the top-2 combine
    grad = jax.grad(
        lambda ws: jnp.sum(
            moe_sharded(expert_fn, ws, Wr, x, mesh, capacity_factor=8.0, top_k=2) ** 2
        )
    )(Ws)

    def dense_loss(ws):
        d = jnp.stack([expert_fn(ws[e], x) for e in range(N)], 1)
        o = (
            d[jnp.arange(B), top_idx[:, 0]] * w[:, :1]
            + d[jnp.arange(B), top_idx[:, 1]] * w[:, 1:]
        )
        return jnp.sum(o ** 2)

    ref_grad = jax.grad(dense_loss)(Ws)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_grad), atol=1e-4)


def test_moe_drop_fraction_visible(cpu_mesh_devices):
    """Tokens beyond capacity are dropped — round 1 did this silently; the
    drop fraction must now be reported."""
    import jax
    import jax.numpy as jnp

    from raydp_tpu.parallel import make_mesh, moe_sharded

    N, D, B = 4, 8, 64
    mesh = make_mesh({"ep": N}, jax.devices()[:N])
    rng = np.random.default_rng(22)
    Ws = jnp.asarray(rng.standard_normal((N, D, D)), jnp.float32)
    # router biased hard toward expert 0 → guaranteed overflow at cf=1.0
    Wr = jnp.asarray(
        np.concatenate(
            [np.full((D, 1), 3.0), np.zeros((D, N - 1))], axis=1
        ),
        jnp.float32,
    )
    x = jnp.abs(jnp.asarray(rng.standard_normal((B, D)), jnp.float32))

    _, aux = moe_sharded(
        lambda W, t: t @ W, Ws, Wr, x, mesh, capacity_factor=1.0, top_k=1,
        return_aux=True,
    )
    assert float(aux["drop_fraction"]) > 0.2
    assert float(aux["load_balance_loss"]) > 1.5  # collapsed router


def test_moe_aux_loss_reduces_imbalance(cpu_mesh_devices):
    """Training the router against load_balance_loss must spread the load:
    the loss falls toward 1.0 (perfect balance) and drops disappear."""
    import jax
    import jax.numpy as jnp
    import optax

    from raydp_tpu.parallel import make_mesh, moe_sharded

    N, D, B = 4, 8, 64
    mesh = make_mesh({"ep": N}, jax.devices()[:N])
    rng = np.random.default_rng(23)
    Ws = jnp.asarray(rng.standard_normal((N, D, D)) * 0.5, jnp.float32)
    # collapsed start: every token prefers expert 0
    Wr0 = jnp.asarray(
        np.concatenate([np.full((D, 1), 2.0), np.zeros((D, N - 1))], 1)
        + rng.standard_normal((D, N)) * 0.01,
        jnp.float32,
    )
    x = jnp.abs(jnp.asarray(rng.standard_normal((B, D)), jnp.float32))

    def aux_of(wr):
        _, aux = moe_sharded(
            lambda W, t: t @ W, Ws, wr, x, mesh, capacity_factor=1.25,
            top_k=2, return_aux=True,
        )
        return aux["load_balance_loss"], aux["drop_fraction"]

    tx = optax.adam(0.05)
    opt_state = tx.init(Wr0)

    @jax.jit
    def step(wr, opt_state):
        lb, _ = aux_of(wr)
        g = jax.grad(lambda w: aux_of(w)[0])(wr)
        updates, opt_state = tx.update(g, opt_state, wr)
        return optax.apply_updates(wr, updates), opt_state, lb

    wr = Wr0
    lb_first = None
    for _ in range(120):
        wr, opt_state, lb = step(wr, opt_state)
        if lb_first is None:
            lb_first = float(lb)
    lb_last, drop_last = (float(v) for v in aux_of(wr))
    assert lb_first > 1.5, f"start not collapsed: {lb_first}"
    assert lb_last < 1.15, f"aux loss failed to rebalance: {lb_last}"
    assert drop_last < 0.05, f"drops persist after rebalancing: {drop_last}"


def test_flash_attention_composes_with_shard_map(cpu_mesh_devices):
    """Mosaic kernels can't be AUTO-partitioned, but under shard_map (manual
    partitioning) the flash kernel runs per shard — the composition ring
    attention's per-device block math will use."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from raydp_tpu.ops import flash_attention
    from raydp_tpu.ops.flash_attention import _reference
    from raydp_tpu.parallel import make_mesh
    from raydp_tpu.parallel.sharding import shard_map_compat

    mesh = make_mesh({"data": 4}, jax.devices()[:4])
    rng = np.random.default_rng(13)
    q, k, v = (
        jnp.asarray(rng.standard_normal((8, 2, 64, 16)), jnp.float32)
        for _ in range(3)
    )
    spec = P("data", None, None, None)  # batch-sharded; attention is local
    # check_vma=False: the pallas interpreter can't reconcile invariant grid
    # slices with varying operands (JAX's documented workaround);
    # shard_map_compat translates it to check_rep on pre-typeof jax
    out = shard_map_compat(
        lambda q_, k_, v_: flash_attention(q_, k_, v_, True, 32, 32),
        mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False,
    )(q, k, v)
    ref = _reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_quantize_int8_roundtrip():
    import jax.numpy as jnp

    from raydp_tpu.ops import dequantize_int8, quantize_int8

    rng = np.random.default_rng(12)
    x = jnp.asarray(rng.standard_normal((32, 128)) * 2, jnp.float32)
    values, scales = quantize_int8(x)
    assert values.dtype == jnp.int8 and scales.shape == (32, 1)
    back = dequantize_int8(values, scales)
    quantum = float(jnp.max(scales))
    assert float(jnp.max(jnp.abs(back - x))) <= quantum + 1e-6

    # stochastic path (jax.random off-TPU; the pallas kernel is TPU-only and
    # validated on real hardware): unbiased
    sv, ss = quantize_int8(x, seed=3, stochastic=True)
    sback = dequantize_int8(sv, ss)
    assert abs(float(jnp.mean(sback - x))) < quantum / 10


def test_int8_matmul_and_quantized_mlp():
    """int8_matmul: forward approximates the float matmul within the
    per-row/column quantization bound; gradients are the exact-matmul
    straight-through grads. The quantized_mlp model flag keeps the SAME
    param tree as the bf16 path (nn.Dense with a custom dot_general), so
    checkpoints interchange; training through it converges."""
    import jax
    import jax.numpy as jnp
    import optax

    from raydp_tpu.models import TransformerLM
    from raydp_tpu.ops import int8_matmul

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((128, 96)) * 0.1, jnp.float32)
    y = int8_matmul(x, w)
    ref = x @ w
    assert float(jnp.max(jnp.abs(y - ref)) / jnp.max(jnp.abs(ref))) < 0.03
    gx, gw = jax.grad(lambda a, b: jnp.sum(int8_matmul(a, b) ** 2), (0, 1))(x, w)
    assert gx.shape == x.shape and gw.shape == w.shape
    assert np.isfinite(np.asarray(gw)).all()

    # identical param trees: a bf16 checkpoint loads into the int8 model
    kw = dict(
        vocab_size=64, d_model=64, num_heads=4, num_layers=2, max_len=64,
        dtype=jnp.float32,
    )
    tok = jnp.asarray(rng.integers(0, 64, (2, 32)), jnp.int32)
    p_plain = TransformerLM(**kw).init(jax.random.PRNGKey(0), tok)
    quant = TransformerLM(quantized_mlp=True, **kw)
    p_quant = quant.init(jax.random.PRNGKey(0), tok)
    assert jax.tree.structure(p_plain) == jax.tree.structure(p_quant)
    quant.apply(p_plain, tok)  # bf16-trained params run on the int8 path

    # training converges through the straight-through estimator
    tx = optax.adam(3e-3)
    p, o = p_quant, tx.init(p_quant)

    @jax.jit
    def step(p, o):
        def f(pp):
            lg = quant.apply(pp, tok)
            return optax.softmax_cross_entropy_with_integer_labels(
                lg, jnp.roll(tok, -1, 1)
            ).mean()

        l, g = jax.value_and_grad(f)(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, l

    l0 = None
    for _ in range(60):
        p, o, l = step(p, o)
        if l0 is None:
            l0 = float(l)
    assert float(l) < l0 * 0.5


def test_make_mesh_shapes(cpu_mesh_devices):
    import jax
    from raydp_tpu.parallel import make_mesh, mesh_axis_size

    mesh = make_mesh({"data": -1}, jax.devices()[:8])
    assert mesh_axis_size(mesh, "data") == 8
    mesh = make_mesh({"data": 2, "model": -1}, jax.devices()[:8])
    assert mesh.shape["model"] == 4
    with pytest.raises(ValueError):
        make_mesh({"data": 16}, jax.devices()[:8])


def test_ring_attention_backward_matches_full(mesh8):
    """The ring-attention custom VJP (second ring pass rotating dk/dv with
    their K/V blocks, probabilities rebuilt from the global logsumexp) must
    match gradients through single-device full attention — einsum AND flash
    block kernels, causal and not."""
    import jax
    import jax.numpy as jnp

    from raydp_tpu.parallel import full_attention, ring_attention_sharded

    rng = np.random.default_rng(31)
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 2, 256, 16)), jnp.float32)
        for _ in range(3)
    )
    g = jnp.asarray(rng.standard_normal((1, 2, 256, 16)), jnp.float32)

    for causal in (False, True):
        _, ref_vjp = jax.vjp(
            lambda a, b, c: full_attention(a, b, c, causal=causal), q, k, v
        )
        ref_grads = ref_vjp(g)
        for use_flash in (False, True):
            _, vjp = jax.vjp(
                lambda a, b, c: ring_attention_sharded(
                    a, b, c, mesh8, axis="sp", causal=causal,
                    use_flash=use_flash,
                ),
                q, k, v,
            )
            grads = vjp(g)
            for name, got, want in zip(("dq", "dk", "dv"), grads, ref_grads):
                np.testing.assert_allclose(
                    np.asarray(got), np.asarray(want), atol=2e-4,
                    err_msg=f"causal={causal} flash={use_flash} {name}",
                )


def test_ulysses_flash_matches_full(mesh8):
    """Ulysses with the fused flash kernel on the gathered local sequence —
    exact vs full attention, forward and backward."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from raydp_tpu.parallel import full_attention, ulysses_attention
    from raydp_tpu.parallel.sharding import shard_map_compat

    rng = np.random.default_rng(4)
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, 8, 64, 8)), jnp.float32)
        for _ in range(3)
    )
    spec = P(None, None, "sp", None)
    fn = shard_map_compat(
        partial(ulysses_attention, axis_name="sp", causal=True, use_flash=True),
        mesh=mesh8, in_specs=(spec,) * 3, out_specs=spec, check_vma=False,
    )
    out, vjp = jax.vjp(fn, q, k, v)
    ref, rvjp = jax.vjp(partial(full_attention, causal=True), q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=5e-5)
    g = jnp.asarray(rng.standard_normal(out.shape), jnp.float32)
    for a, b in zip(vjp(g), rvjp(g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)
