"""Millisecond control plane: compiled-plan cache + whole-plan dispatch.

Parity discipline mirrors PR 3's indexed-vs-legacy shuffle tests: every new
path (plan cache, run_plan dispatch, head-bypass location pushing) has an A/B
toggle and must produce byte-identical Arrow results against the legacy
staged path. Plus the control-plane budgets the roadmap demands: a second
execution of an identical query shape performs zero planning work and costs
at most 2 head RPCs (asserted from ``last_query_stats``'s new counters).
"""

import numpy as np
import pandas as pd
import pytest

import raydp_tpu
from raydp_tpu.etl import functions as F
from raydp_tpu.store import object_store as store


@pytest.fixture(scope="module")
def session():
    s = raydp_tpu.init_etl(
        "test-plan-cache", num_executors=2, executor_cores=2,
        executor_memory="300M",
    )
    yield s
    raydp_tpu.stop_etl()


def _pdf(n=300, seed=3):
    rng = np.random.default_rng(seed)
    return pd.DataFrame(
        {
            "a": np.arange(n, dtype=np.int64),
            "k": rng.integers(0, 5, n),
            "v": rng.random(n),
        }
    )


def _ab(session, build):
    """build() under the FULL legacy control plane (no cache, no compiled
    dispatch, no head bypass) vs under the compiled one; returns both."""
    planner = session._planner
    saved = (planner.plan_cache, planner.compiled_dispatch, planner.head_bypass)
    try:
        planner.plan_cache = False
        planner.compiled_dispatch = False
        planner.head_bypass = False
        store.set_location_cache(False)
        legacy = build()
        planner.plan_cache, planner.compiled_dispatch, planner.head_bypass = (
            True, True, True,
        )
        store.set_location_cache(True)
        compiled = build()
        # and once more from the warm cache — cached-plan vs fresh-plan
        cached = build()
    finally:
        (
            planner.plan_cache, planner.compiled_dispatch, planner.head_bypass
        ) = saved
        store.set_location_cache(saved[2])
    return legacy, compiled, cached


def test_narrow_chain_ab_identical(session):
    df = (
        session.from_pandas(_pdf(), num_partitions=4)
        .with_column("w", F.col("v") * 3.0)
        .with_column("z", F.col("w") + F.col("a"))
        .filter(F.col("k") > 1)
        .select("a", "k", "z")
    )
    legacy, compiled, cached = _ab(session, df.to_arrow)
    assert legacy.equals(compiled)
    assert legacy.equals(cached)


def test_exchange_shapes_ab_identical(session):
    df = session.from_pandas(_pdf(), num_partitions=4)

    shapes = {
        "groupby": lambda: (
            df.group_by("k")
            .agg(F.sum("v").alias("sv"), F.count("*").alias("c"))
            .to_arrow()
            .sort_by("k")
        ),
        "repartition": lambda: df.repartition(3).to_arrow().sort_by("a"),
        "distinct": lambda: (
            df.select("k").distinct().to_arrow().sort_by("k")
        ),
        "window": lambda: (
            df.with_column(
                "rn", F.row_number().over(partition_by=["k"], order_by=["a"])
            )
            .to_arrow()
            .sort_by("a")
        ),
    }
    for name, build in shapes.items():
        legacy, compiled, cached = _ab(session, build)
        assert legacy.equals(compiled), name
        assert legacy.equals(cached), name


def test_second_execution_zero_planning_and_head_rpc_budget(session):
    """The acceptance budget: an identical query shape re-executed performs
    ZERO planning work (plan-cache hit, no misses) and costs ≤ 2 head RPCs
    on the driver."""
    df = (
        session.from_pandas(_pdf(seed=11), num_partitions=4)
        .with_column("b2", F.col("v") * 2.0)
        .filter(F.col("k") < 4)
    )
    first = df.count()
    warm_counts = []
    for _ in range(2):
        assert df.count() == first
        stats = session.last_query_stats
        assert stats["plan_cache"]["hit"] is True
        assert stats["plan_cache"]["misses"] == 0
        assert stats["rpc"]["head_rpcs"] <= 2, stats["rpc"]
        # one whole-plan dispatch per executor, nothing else
        assert stats["rpc"]["actor_dispatches"] <= len(session.executors)
        warm_counts.append(stats["rpc"]["head_rpcs"])
    # warm exchange too (groupby: map registrations happen executor-side;
    # the driver pays at most locality + intermediate-delete round trips)
    agg = df.group_by("k").agg(F.sum("v").alias("s"))
    agg.count()
    agg.count()
    stats = session.last_query_stats
    assert stats["plan_cache"]["hit"] is True
    assert stats["rpc"]["head_rpcs"] <= 2, stats["rpc"]


def test_literal_slots_rebind_without_recompile(session):
    """Same query shape, different literal: the plan cache must HIT (the
    literal is a parameter slot) and the result must reflect the NEW value."""
    pdf = _pdf(seed=7)
    df = session.from_pandas(pdf, num_partitions=4)

    def q(cut):
        return (
            df.filter(F.col("a") < F.lit(cut))
            .with_column("w", F.col("v") + F.lit(float(cut)))
            .to_arrow()
        )

    t1 = q(50)
    assert t1.num_rows == 50
    t2 = q(120)
    stats = session.last_query_stats
    assert stats["plan_cache"]["hit"] is True, stats["plan_cache"]
    assert t2.num_rows == 120
    expect = pdf[pdf.a < 120]
    assert np.allclose(
        np.sort(t2.column("w").to_numpy()),
        np.sort((expect.v + 120.0).to_numpy()),
    )


def test_invalidation_on_conf_flip_and_schema_change(session):
    """A lowering-relevant conf flip and an input-schema change must each
    RECOMPILE (cache miss), never serve the stale program."""
    planner = session._planner
    df = session.from_pandas(_pdf(seed=5), num_partitions=3)
    build = lambda frame: frame.group_by("k").agg(  # noqa: E731
        F.sum("v").alias("s")
    ).to_arrow().sort_by("k")
    base = build(df)
    assert build(df).equals(base)
    assert session.last_query_stats["plan_cache"]["hit"] is True
    saved = planner.shuffle_indexed_blocks
    try:
        planner.shuffle_indexed_blocks = not saved
        assert build(df).equals(base)  # conf flip → new fingerprint
        stats = session.last_query_stats
        assert stats["plan_cache"]["misses"] == 1, stats["plan_cache"]
    finally:
        planner.shuffle_indexed_blocks = saved
    # schema change: same query text, float32 value column → recompile
    pdf2 = _pdf(seed=5)
    pdf2["v"] = pdf2["v"].astype(np.float32)
    df2 = session.from_pandas(pdf2, num_partitions=3)
    build(df2)
    stats = session.last_query_stats
    assert stats["plan_cache"]["misses"] == 1, stats["plan_cache"]


def test_program_cache_miss_after_executor_restart(session):
    """An executor restart drops its resident programs; the driver's next
    warm dispatch gets ProgramCacheMiss and must re-ship the program body
    transparently (same results, still a driver-side cache hit)."""
    from raydp_tpu.cluster.common import ActorState

    df = (
        session.from_pandas(_pdf(seed=13), num_partitions=4)
        .with_column("r", F.col("v") * 5.0)
    )
    import time

    before = df.to_arrow()
    victim = session.executors[0]
    old_inc = victim._record().incarnation
    victim.kill(no_restart=False)  # restartable kill: same identity returns
    deadline = time.monotonic() + 60
    while True:  # wait for the NEW incarnation to come up (kill is async)
        record = victim._record()
        if record.incarnation > old_inc and record.state == ActorState.ALIVE:
            break
        assert time.monotonic() < deadline, record
        time.sleep(0.05)
    after = df.to_arrow()
    assert before.equals(after)
    assert session.last_query_stats["plan_cache"]["hit"] is True


def test_uncompilable_shapes_still_work(session):
    """Joins/sorts/limits stay on the recursive driver: counted as
    ``unsupported``, executed exactly as before."""
    pdf = _pdf(seed=17)
    df = session.from_pandas(pdf, num_partitions=3)
    other = session.from_pandas(
        pd.DataFrame({"k": np.arange(5), "name": [f"n{i}" for i in range(5)]}),
        num_partitions=2,
    )
    joined = df.join(other, on=["k"]).to_arrow()
    assert joined.num_rows == len(pdf)
    stats = session.last_query_stats
    assert stats["plan_cache"]["unsupported"] >= 1
    assert stats["plan_cache"]["hits"] == 0
