"""End-to-end benchmark: ETL → exchange → train on the NYCTaxi MLP workload.

The reference publishes no numbers (BASELINE.md); the tracked north-star is
samples/sec/chip for the full pipeline vs pure-JAX training throughput on the
same model/data (target ≥ 0.8× — i.e., the framework's data path must not
drag the chip). Prints ONE JSON line.

Runs on whatever jax.devices() provides: the real TPU chip under the driver,
CPU elsewhere (JAX_PLATFORMS=cpu honored despite the image's pre-registered
TPU plugin).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _maybe_force_cpu():
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # raydp-lint: disable=swallowed-exceptions (platform already pinned at import; bench proceeds either way)
            pass


def make_taxi_source(n_rows: int):
    """Synthesize the NYCTaxi-shaped SOURCE data (stands in for the CSV the
    reference examples read from disk — generation is not ETL and is timed
    separately as data_gen_s)."""
    import pandas as pd

    rng = np.random.default_rng(7)
    base = pd.Timestamp("2020-01-01").value // 10**9
    pickup = base + rng.integers(0, 30 * 24 * 3600, n_rows)
    duration = rng.integers(120, 3600, n_rows)
    return pd.DataFrame(
        {
            "pickup_ts": pd.to_datetime(pickup, unit="s"),
            "passenger_count": rng.integers(1, 6, n_rows).astype(np.int64),
            "pickup_longitude": -74.0 + rng.random(n_rows) * 0.1,
            "pickup_latitude": 40.7 + rng.random(n_rows) * 0.1,
            "dropoff_longitude": -74.0 + rng.random(n_rows) * 0.1,
            "dropoff_latitude": 40.7 + rng.random(n_rows) * 0.1,
            "fare_amount": (2.5 + duration / 240.0 + rng.random(n_rows)).astype(
                np.float64
            ),
        }
    )


def make_taxi_frame(session, pdf, parts: int):
    """The reference pipeline's feature engineering (examples/data_process.py:
    datetime decomposition, distance) on an already-loaded source frame."""
    from raydp_tpu.etl import functions as F

    df = session.from_pandas(pdf, num_partitions=parts)
    df = (
        df.with_column("hour", F.hour("pickup_ts").cast("float32"))
        .with_column("dow", F.dayofweek("pickup_ts").cast("float32"))
        .with_column("dx", (F.col("dropoff_longitude") - F.col("pickup_longitude")))
        .with_column("dy", (F.col("dropoff_latitude") - F.col("pickup_latitude")))
        .with_column(
            "dist",
            F.sqrt(F.col("dx") * F.col("dx") + F.col("dy") * F.col("dy")).cast(
                "float32"
            ),
        )
        .with_column("pc", F.col("passenger_count").cast("float32"))
        .with_column("label", F.col("fare_amount").cast("float32"))
        .select("hour", "dow", "dist", "pc", "label")
    )
    return df


FEATURES = ["hour", "dow", "dist", "pc"]


def bench_framework(n_rows: int, batch: int, epochs: int):
    import raydp_tpu
    from raydp_tpu.estimator import JaxEstimator
    from raydp_tpu.exchange import dataframe_to_dataset
    from raydp_tpu.models import MLPRegressor

    t0 = time.perf_counter()
    pdf = make_taxi_source(n_rows)
    t_gen = time.perf_counter() - t0

    t0 = time.perf_counter()
    session = raydp_tpu.init_etl(
        "bench", num_executors=2, executor_cores=2, executor_memory="1G"
    )
    t_boot = time.perf_counter() - t0
    t0 = time.perf_counter()
    # 4 partitions = the pool's parallel slots (2 executors x 2 cores)
    df = make_taxi_frame(session, pdf, parts=4)
    # ownership transfer + stop: training runs with the ETL engine's CPUs
    # returned (the reference's stop_spark_after_conversion pattern)
    ds = dataframe_to_dataset(df, _use_owner=True)
    etl_breakdown = _etl_breakdown(session.last_query_stats)
    # shuffle-plane probe (separately timed, EXCLUDED from etl_query_s so it
    # stays comparable across rounds): an M-map/R-reduce repartition on the
    # same session — its etl_breakdown.shuffle reports blocks == M (indexed
    # single-block map outputs), bytes, and the reduce start lag
    t_sh = time.perf_counter()
    df.repartition(3).count()
    t_shuffle = time.perf_counter() - t_sh
    shuffle_probe = {
        # the probe's measured wall time LAST: _etl_breakdown also carries a
        # "seconds" key (the count-query's span) that must not shadow the
        # t_shuffle actually subtracted from etl_query_s below
        **_etl_breakdown(session.last_query_stats),
        "seconds": round(t_shuffle, 4),
    }
    # interactive-burst probe (separately timed, EXCLUDED from etl_query_s):
    # N repeated queries of one shape — the compiled-plan cache / head-bypass
    # / doorbell warm path the millisecond control plane exists for
    t_b = time.perf_counter()
    burst = interactive_burst(
        session, df, int(os.environ.get("BENCH_BURST", 1000))
    )
    t_burst = time.perf_counter() - t_b
    # streaming-ingest probe (separately timed, EXCLUDED from etl_query_s):
    # a short streaming fit while the ETL session is still ALIVE, so the
    # executor-side decode path is exercised and its evidence (decode off
    # the consumer thread, N-way upload streams, shard-direct feeds) lands
    # in the report. The headline streaming_throughput section below runs
    # post-stop_etl (local-decode fallback) like all training does.
    t_i = time.perf_counter()
    ingest_probe = streaming_ingest_probe(ds, batch)
    t_ingest = time.perf_counter() - t_i
    # recovery probe (separately timed, EXCLUDED from etl_query_s): the
    # same data queried with one injected executor SIGKILL — lineage
    # recovery's wall-clock cost as a first-class bench number
    t_r = time.perf_counter()
    rec_probe = recovery_probe(session, df)
    t_recovery = time.perf_counter() - t_r
    raydp_tpu.stop_etl(cleanup_data=False, del_obj_holder=False)
    t_query = (
        time.perf_counter() - t0 - t_shuffle - t_burst - t_ingest - t_recovery
    )
    t_etl = t_boot + t_query

    est = JaxEstimator(
        model=MLPRegressor(),
        optimizer="adam",
        loss="mse",
        feature_columns=FEATURES,
        label_column="label",
        batch_size=batch,
        num_epochs=epochs,
        learning_rate=1e-3,
        shuffle=True,
        seed=0,
        # donation halves device memory for big models but costs ~10-30%
        # dispatch overhead on this plugin; at bench scale memory is not a
        # constraint and the pure-JAX side doesn't donate either
        donate_state=False,
    )
    trained = (n_rows // batch) * batch * epochs
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    x = rng.random((n_rows, len(FEATURES))).astype(np.float32)
    y = rng.random(n_rows).astype(np.float32)

    def mse(pred, target):
        return jnp.mean((pred.reshape(target.shape) - target) ** 2)

    cmp = interleaved_fit_vs_pure(
        est, ds, trained,
        lambda: pure_jax_throughput(MLPRegressor(), mse, x, y, batch, epochs),
        lambda: pure_jax_scan_throughput(MLPRegressor(), mse, x, y, batch, epochs),
    )
    cmp["eval_sps"] = eval_throughput(est, ds, n_rows)
    cmp["etl_breakdown"] = etl_breakdown
    cmp["shuffle_probe"] = shuffle_probe
    cmp["streaming_ingest_probe"] = ingest_probe
    cmp["recovery_probe"] = rec_probe
    cmp["recovery_overhead"] = rec_probe.get("recovery_overhead")
    cmp["recovery_overhead_service_on"] = rec_probe.get(
        "recovery_overhead_service_on"
    )
    cmp.update(burst)
    cmp.update(
        fair_e2e_fields(pandas_taxi_etl, pdf, trained, t_boot, t_query, cmp)
    )
    cmp.update(
        streaming_throughput(MLPRegressor(), FEATURES, ds, trained, batch, epochs)
    )
    cmp["streaming_vs_scan"] = round(
        cmp["streaming_sps"] / cmp["train_only_sps"], 4
    )
    cmp["streaming_hybrid_vs_scan"] = round(
        cmp["streaming_hybrid_sps"] / cmp["train_only_sps"], 4
    )
    return trained, t_gen, t_etl, cmp


def streaming_ingest_probe(ds, batch: int) -> dict:
    """One short streaming fit with the ETL session ALIVE: the per-span
    Arrow→numpy decode dispatches to the executor pool (decode_segment) and
    the consumer thread only sequences uploads. Reports the fit's
    stream_stats_ — executor_decode must read true here, where the headline
    streaming section (post-stop_etl) legitimately falls back to local."""
    from raydp_tpu.estimator import JaxEstimator
    from raydp_tpu.models import MLPRegressor

    est = JaxEstimator(
        model=MLPRegressor(), optimizer="adam", loss="mse",
        feature_columns=FEATURES, label_column="label",
        batch_size=batch, num_epochs=2, learning_rate=1e-3,
        shuffle=False, seed=0, donate_state=False, streaming=True,
    )
    est.fit(ds)
    stats = dict(getattr(est, "stream_stats_", {}))
    for k in ("producer_idle_s", "consumer_idle_s"):
        if k in stats:
            stats[k] = round(stats[k], 3)
    # evidence caveat that belongs IN the artifact: on a 2-core box the
    # executor decode processes compete with the training scan for the same
    # cores, so this probe's consumer_idle_s reads high here — the gated
    # number is the headline streaming_pipeline one (local decode, like all
    # post-stop_etl training). The probe exists to prove the executor path
    # runs and to carry its stats on hosts with cores to spare.
    stats["note"] = "live-session probe incl. compile; 2-core boxes starve executor decode"
    return stats


def recovery_probe(session, df) -> dict:
    """BOTH recovery tiers (docs/fault_tolerance.md "Ownership tiers"), the
    same query with ONE injected executor SIGKILL each:

    - ``service_on`` — the default arm: the per-host block service owns the
      blocks, so executor death loses nothing. Expected ``recovery_overhead``
      ≈ 1.0x with ZERO re-executed tasks (the handoff must be ~free).
    - ``service_off`` — the head's service registration is dropped for this
      arm (store/block_service.deregister_service), restoring PR 8's
      executor-owned behavior: the kill is real loss and lineage recovery
      re-executes the producing tasks (~7.6x on a 4.5ms query at r08).

    Reports wall-clock ratios, re-execution counts, and correctness per
    tier; the top-level ``recovery_overhead`` stays the LINEAGE tier's ratio
    (continuity with r08's meaning). Separately timed, EXCLUDED from
    etl_query_s."""
    from raydp_tpu import obs
    from raydp_tpu.exchange import dataframe_to_dataset, dataset_to_dataframe
    from raydp_tpu.store import block_service as bs
    from raydp_tpu.store import object_store as store

    from tools.chaos import block_owner_executor, kill_executor

    pool = len(session.executors)

    def one_tier(expect_reexec: bool) -> dict:
        ds = dataframe_to_dataset(df.repartition(4))
        q = dataset_to_dataframe(session, ds)
        q.count()  # warm-up: compile + cache the plan so clean_s and
        # recovered_s compare warm-vs-warm — a cold clean run would fold the
        # one-time compile into the denominator and understate the overhead
        t0 = time.perf_counter()
        clean_rows = q.count()
        clean_s = time.perf_counter() - t0
        before = obs.metrics.counter("lineage.reexecuted_tasks").value
        if expect_reexec:
            # the lineage arm needs a victim that OWNS blocks (real loss)
            victim = block_owner_executor(session, ds)
        else:
            # the service arm owns the blocks itself: any executor works
            # (and none may own blocks — that is the point)
            victim = session.executors[0] if session.executors else None
        if victim is None:
            # nothing suitable to kill (stale pool / ownership race):
            # report a failed tier instead of crashing the whole bench
            try:
                store.delete(ds.blocks)
            except Exception:  # raydp-lint: disable=swallowed-exceptions (probe cleanup best-effort; blocks die with the session)
                pass
            return {"ok": False, "note": "no suitable victim to kill"}
        kill_executor(session, handle=victim)
        time.sleep(0.3)  # let the head's owner-death bookkeeping land
        recovered_rows = None
        error = None
        t0 = time.perf_counter()
        try:
            # a recovery regression must surface as recovery_probe.ok=false
            # in the artifact (perf_smoke gates on it), NOT crash the bench
            recovered_rows = q.count()
        except Exception as exc:
            error = repr(exc)[:300]
        recovered_s = time.perf_counter() - t0
        reexecuted = int(
            obs.metrics.counter("lineage.reexecuted_tasks").value - before
        )
        session.request_total_executors(pool)  # restore for later probes
        try:
            store.delete(ds.blocks)
        except Exception:  # raydp-lint: disable=swallowed-exceptions (probe cleanup best-effort; blocks die with the session)
            pass
        out = {
            "clean_s": round(clean_s, 4),
            "recovered_s": round(recovered_s, 4),
            "recovery_overhead": (
                round(recovered_s / clean_s, 3) if clean_s > 0 else None
            ),
            "reexecuted_tasks": reexecuted,
            "ok": bool(
                recovered_rows == clean_rows
                and (reexecuted >= 1 if expect_reexec else reexecuted == 0)
            ),
        }
        if error is not None:
            out["error"] = error
        return out

    svc = getattr(session, "block_service", None)
    if svc is not None:
        service_on = one_tier(expect_reexec=False)
        # flip to the PR 8 arm WITHOUT a second session: deregistering at
        # the head makes future registrations keep executor ownership
        bs.deregister_service(svc._actor_id)
        try:
            service_off = one_tier(expect_reexec=True)
        finally:
            try:
                bs.register_service(svc._actor_id)
            except Exception:  # raydp-lint: disable=swallowed-exceptions (probe teardown best-effort; the session is stopped right after)
                pass
    else:
        service_on = {"ok": False, "note": "session has no block service"}
        service_off = one_tier(expect_reexec=True)
    return {
        "service_on": service_on,
        "service_off": service_off,
        "recovery_overhead": service_off.get("recovery_overhead"),
        "recovery_overhead_service_on": service_on.get("recovery_overhead"),
        "reexecuted_tasks": service_off.get("reexecuted_tasks"),
        "ok": bool(service_on.get("ok") and service_off.get("ok")),
    }


def serving_probe() -> dict:
    """Closed-loop serving load generator (raydp_tpu.serve, docs/serving.md)
    plus a kill-during-load recovery probe.

    A tiny model checkpoint is published directly (init + save — the probe
    measures SERVING, training throughput has its own sections), deployed on
    two replicas, and driven by N closed-loop clients (each waits for its
    response before sending the next request) for a fixed wall-clock window.
    Reports p50/p99 request latency, sustained requests/sec, and SLO
    attainment at a fixed p99 SLO (``BENCH_SERVE_SLO_MS``, default 250ms —
    generous on a 2-core CPU box; the gate exists to catch structural
    regressions like a compile or a fresh connect on the request path).

    The recovery probe then replays a FIXED request list twice — clean, and
    with a replica SIGKILLed mid-stream — under a single batch bucket
    (deterministic shapes), gating zero dropped requests and byte-identical
    responses, the same contract the chaos scenario pins in CI."""
    import tempfile
    import threading

    import jax

    from raydp_tpu import serve
    from raydp_tpu.models import MLPRegressor

    slo_ms = float(os.environ.get("BENCH_SERVE_SLO_MS", 250.0))
    duration_s = float(os.environ.get("BENCH_SERVE_SECONDS", 3.0))
    n_clients = int(os.environ.get("BENCH_SERVE_CLIENTS", 4))

    model = MLPRegressor(hidden=(32, 16))
    rng = np.random.default_rng(11)
    x = rng.random((1024, len(FEATURES))).astype(np.float32)
    ckpt_dir = tempfile.mkdtemp(prefix="bench-serve-ckpt-")
    # publish weights through the same estimator checkpoint channel the
    # replicas load from
    from raydp_tpu.estimator import JaxEstimator

    est = JaxEstimator(
        model=model, feature_columns=FEATURES, checkpoint_dir=ckpt_dir
    )
    params = model.init(jax.random.PRNGKey(0), x[:1])
    est._save_checkpoint(params, 0, {})

    dep = None
    try:
        t_spinup = time.perf_counter()
        dep = serve.deploy(
            est, replicas=2, example=x[0],
            conf={"serve.max_batch_size": 16,
                  "serve.autoscale.tick_s": 0.1},
        )
        spinup_s = time.perf_counter() - t_spinup

        # -- closed-loop load ------------------------------------------
        latencies: list = []
        lat_lock = threading.Lock()
        stop_at = time.perf_counter() + duration_s

        def client(seed: int):
            local = []
            i = seed
            while time.perf_counter() < stop_at:
                t0 = time.perf_counter()
                dep.predict(x[i % 1024 : i % 1024 + 1])
                local.append(time.perf_counter() - t0)
                i += 1
            with lat_lock:
                latencies.extend(local)

        threads = [
            threading.Thread(target=client, args=(k * 31,))
            for k in range(n_clients)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        latencies.sort()
        n = len(latencies)
        p50_ms = latencies[n // 2] * 1000 if n else None
        p99_ms = (
            latencies[min(n - 1, int(n * 0.99))] * 1000 if n else None
        )
        attained = (
            sum(1 for s in latencies if s * 1000 <= slo_ms) / n if n else 0.0
        )

        # -- kill-during-load recovery probe ---------------------------
        # deterministic shapes for the byte-identity gate: route every
        # dispatch into the one 16-row bucket for this phase. The probe
        # body is tools/chaos.serve_kill_probe — the SAME contract the CI
        # chaos scenario gates, one implementation
        from tools.chaos import serve_kill_probe

        dep.close()
        dep = serve.deploy(
            est, replicas=2, example=x[0],
            conf={"serve.max_batch_size": 16,
                  "serve.batch_buckets": [16],
                  "serve.autoscale.tick_s": 0.1},
        )
        kill_probe = serve_kill_probe(dep, x, n_requests=160)
        return {
            "slo_ms": slo_ms,
            "clients": n_clients,
            "requests": n,
            "sustained_rps": round(n / elapsed, 1) if elapsed else None,
            "p50_ms": round(p50_ms, 2) if p50_ms is not None else None,
            "p99_ms": round(p99_ms, 2) if p99_ms is not None else None,
            "slo_attained": round(attained, 4),
            "replica_spinup_s": round(spinup_s / 2, 3),
            "kill_probe": kill_probe,
            "ok": bool(
                n > 0
                and p99_ms is not None
                and p99_ms <= slo_ms
                and kill_probe["ok"]
            ),
        }
    except Exception as exc:  # the bench must report, not crash
        return {"ok": False, "error": repr(exc)[:300]}
    finally:
        if dep is not None:
            try:
                dep.close()
            except Exception:  # raydp-lint: disable=swallowed-exceptions (probe teardown best-effort)
                pass


def _decode_kernel_parity() -> dict:
    """In-process kernel-family parity evidence for the decode bench: the
    two bitwise contracts the serving numbers rest on, re-proved on the
    box that produced them (the same checks tests/test_flash_decode.py
    gates, one shape each — evidence in the snapshot, not just in CI).

    - one-pass deferred-rescale body ≡ reference body, bit-for-bit;
    - flash_decode over a kv_len-row cache ≡ row kv_len-1 of a causal
      prefill at the full fixed cache shape, bit-for-bit (the failover
      re-prefill contract)."""
    import jax.numpy as jnp

    from raydp_tpu.ops.flash_attention import (
        _flash_call, flash_attention, flash_decode,
    )

    b, h, tcap, d = 1, 2, 128, 32
    kv_len = 37
    rng = np.random.default_rng(23)
    q = jnp.asarray(rng.standard_normal((b, h, tcap, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, tcap, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, tcap, d)), jnp.float32)

    onepass_out = {}
    for onepass in (False, True):
        o, m, l = _flash_call(  # noqa: E741
            q, k, v, 0, 0, True, None, None, None,
            normalize=True, onepass=onepass,
        )
        onepass_out[onepass] = (np.asarray(o), np.asarray(m), np.asarray(l))
    onepass_ok = all(
        np.array_equal(a, b_)
        for a, b_ in zip(onepass_out[False], onepass_out[True])
    )

    ref = flash_attention(q, k, v, True)
    got = flash_decode(
        q[:, :, kv_len - 1: kv_len], k, v,
        jnp.full((b,), kv_len, jnp.int32),
    )
    decode_ok = np.array_equal(
        np.asarray(got), np.asarray(ref[:, :, kv_len - 1: kv_len])
    )
    return {
        "onepass_bit_identical": bool(onepass_ok),
        "decode_vs_prefill_bit_identical": bool(decode_ok),
        "ok": bool(onepass_ok and decode_ok),
    }


def decode_serving_probe() -> dict:
    """Streaming decode load generator (docs/serving.md "Decode serving").

    A tiny TransformerLM checkpoint is published through the estimator
    checkpoint channel and deployed on two decode-enabled replicas; N
    closed-loop clients each drive ``dep.stream`` back to back for a fixed
    wall-clock window, timestamping every token. Reports sustained
    ``decode_tokens_per_sec`` across the whole pool, TTFT (first token of
    each stream, the prefill + queue cost), and the per-token p99 over
    inter-token gaps under multi-client load — gated against a fixed SLO
    (``BENCH_DECODE_TOKEN_SLO_MS``, default 1000ms: generous on a 2-core
    CPU box running the pallas interpreter; the gate catches structural
    regressions — a compile inside the decode loop, a stalled scheduler —
    not kernel speed, which MFU tracks on real chips).

    ``kernel_parity`` re-proves the bitwise kernel contracts in-process so
    every committed snapshot carries the parity evidence next to the
    throughput numbers it justifies."""
    import tempfile
    import threading

    import jax
    import jax.numpy as jnp

    from raydp_tpu import serve
    from raydp_tpu.estimator import JaxEstimator
    from raydp_tpu.models import TransformerLM

    slo_ms = float(os.environ.get("BENCH_DECODE_TOKEN_SLO_MS", 1000.0))
    duration_s = float(os.environ.get("BENCH_DECODE_SECONDS", 4.0))
    n_clients = int(os.environ.get("BENCH_DECODE_CLIENTS", 3))
    max_new = int(os.environ.get("BENCH_DECODE_MAX_NEW", 16))

    parity = _decode_kernel_parity()

    vocab = 64
    model = TransformerLM(
        vocab_size=vocab, d_model=32, num_heads=2, num_layers=2,
        max_len=256, attn_impl="flash", dtype=jnp.float32,
    )
    ckpt_dir = tempfile.mkdtemp(prefix="bench-decode-ckpt-")
    est = JaxEstimator(model=model, checkpoint_dir=ckpt_dir)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )
    est._save_checkpoint(params, 0, {})

    dep = None
    try:
        dep = serve.deploy(
            model=model, checkpoint_dir=ckpt_dir, replicas=2,
            conf={
                "serve.decode.enabled": True,
                "serve.decode.capacity_tokens": 128,
                "serve.decode.page_tokens": 32,
                "serve.decode.max_seqs": 4,
                "serve.decode.max_new_tokens": max_new,
            },
        )

        rng = np.random.default_rng(17)
        prompts = [
            [int(t) for t in rng.integers(0, vocab, rng.integers(3, 12))]
            for _ in range(32)
        ]

        # warm BOTH replicas' decode engines (stream round-robins, so two
        # back-to-back streams hit both): the prefill + decode-step jit
        # compiles land outside the measured window, the same warm-path
        # discipline as every other probe — the gate is about the decode
        # loop's structure, not first-call XLA cost
        for _ in range(2):
            dep.generate(prompts[0], 2, timeout=300)

        ttfts: list = []
        gaps: list = []
        token_count = [0]
        stream_count = [0]
        errors: list = []
        lock = threading.Lock()
        stop_at = time.perf_counter() + duration_s

        def client(seed: int):
            local_ttft, local_gaps, tokens, streams = [], [], 0, 0
            i = seed
            while time.perf_counter() < stop_at:
                t_prev = time.perf_counter()
                first = True
                try:
                    for _tok in dep.stream(
                        prompts[i % len(prompts)], max_new, timeout=120
                    ):
                        now = time.perf_counter()
                        if first:
                            local_ttft.append(now - t_prev)
                            first = False
                        else:
                            local_gaps.append(now - t_prev)
                        t_prev = now
                        tokens += 1
                    streams += 1
                except Exception as exc:  # raydp-lint: disable=swallowed-exceptions (closed-loop driver: failures surface in the errors list the gate checks)
                    with lock:
                        errors.append(repr(exc)[:200])
                    break
                i += 1
            with lock:
                ttfts.extend(local_ttft)
                gaps.extend(local_gaps)
                token_count[0] += tokens
                stream_count[0] += streams

        threads = [
            threading.Thread(target=client, args=(k * 7,))
            for k in range(n_clients)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0

        gaps.sort()
        ttfts.sort()
        n_gaps = len(gaps)
        token_p99_ms = (
            gaps[min(n_gaps - 1, int(n_gaps * 0.99))] * 1000
            if n_gaps else None
        )
        ttft_ms = ttfts[len(ttfts) // 2] * 1000 if ttfts else None
        tokens = token_count[0]
        tps = tokens / elapsed if elapsed else None
        return {
            "clients": n_clients,
            "streams": stream_count[0],
            "tokens": tokens,
            "decode_tokens_per_sec": round(tps, 1) if tps else None,
            "ttft_ms": round(ttft_ms, 2) if ttft_ms is not None else None,
            "token_p99_ms": (
                round(token_p99_ms, 2) if token_p99_ms is not None else None
            ),
            "token_slo_ms": slo_ms,
            "kernel_parity": parity,
            "errors": errors[:3],
            "ok": bool(
                parity["ok"]
                and tokens > 0
                and not errors
                and token_p99_ms is not None
                and token_p99_ms <= slo_ms
            ),
        }
    except Exception as exc:  # the bench must report, not crash
        return {"ok": False, "kernel_parity": parity,
                "error": repr(exc)[:300]}
    finally:
        if dep is not None:
            try:
                dep.close()
            except Exception:  # raydp-lint: disable=swallowed-exceptions (probe teardown best-effort)
                pass


def decode_obs_overhead_probe() -> dict:
    """Decode-observatory overhead: per-token cost of stream tracing at
    sample rate 1.0 (trace minting, prefill + step fan-in span emission)
    plus the always-on stream bookkeeping, tracing ON vs OFF on one
    in-process DecodeEngine (perf_smoke gates the quotient).

    In-process by necessity AND by honesty: a driver-side ``set_enabled``
    cannot reach a deployed replica's process, and the cost under test —
    the engine loop's per-step instrumentation — is process-local anyway.
    Interleaved rounds with rotating lead (the r06 lesson), identical
    sequential stream workload per arm, median-of-round-medians ms/token.
    A local-ingest stub absorbs flushes for the probe's duration so a
    missing/stopped head never adds RPC-retry noise to either arm."""
    import jax
    import jax.numpy as jnp

    from raydp_tpu.models import TransformerLM
    from raydp_tpu.obs import tracing as _tracing
    from raydp_tpu.serve.decode import DecodeEngine

    rounds = int(os.environ.get("BENCH_DECODE_OBS_ROUNDS", 4))
    streams_per_arm = int(os.environ.get("BENCH_DECODE_OBS_STREAMS", 6))
    max_new = int(os.environ.get("BENCH_DECODE_OBS_MAX_NEW", 16))

    vocab = 64
    model = TransformerLM(
        vocab_size=vocab, d_model=32, num_heads=2, num_layers=2,
        max_len=256, attn_impl="flash", dtype=jnp.float32,
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )
    engine = None
    was_enabled = _tracing.enabled()
    _tracing.set_local_ingest(lambda **kw: None)
    try:
        engine = DecodeEngine(
            model, params, capacity_tokens=128, page_tokens=32,
            max_seqs=4, max_new_tokens=max_new,
            # SLO judging ON in both arms: the deadline accounting is part
            # of the always-on plane whose cost this probe bounds
            ttft_slo_ms=1000.0, tpot_slo_ms=1000.0,
        )
        rng = np.random.default_rng(23)
        prompts = [
            [int(t) for t in rng.integers(0, vocab, 8)] for _ in range(8)
        ]

        def one_stream(idx: int, ctx) -> float:
            """Submit + drain one stream; returns ms per emitted token."""
            t0 = time.perf_counter()
            sid = engine.submit(
                prompts[idx % len(prompts)], max_new, trace_ctx=ctx
            )
            tokens: list = []
            deadline = time.monotonic() + 120.0
            while True:
                res = engine.poll(sid, len(tokens))
                tokens.extend(res["tokens"])
                if res["error"]:
                    raise RuntimeError(res["error"])
                if res["done"]:
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError(f"stream {sid} timed out")
                time.sleep(0.001)
            return (time.perf_counter() - t0) * 1000.0 / max(1, len(tokens))

        # warm the prefill + decode-step jits outside the measured rounds
        for k in range(2):
            one_stream(k, None)

        def one_arm(arm_on: bool, base: int) -> float:
            _tracing.set_enabled(arm_on)
            samples = []
            for k in range(max(1, streams_per_arm)):
                ctx = _tracing.mint_context() if arm_on else None
                samples.append(one_stream(base + k, ctx))
            samples.sort()
            return samples[len(samples) // 2]

        ms_on, ms_off = [], []
        for i in range(max(1, rounds)):
            order = ((True, False), (False, True))[i % 2]  # rotating lead
            for arm_on in order:
                p50 = one_arm(arm_on, i * streams_per_arm)
                (ms_on if arm_on else ms_off).append(p50)
        ms_on.sort()
        ms_off.sort()
        on_ms = ms_on[len(ms_on) // 2]
        off_ms = ms_off[len(ms_off) // 2]
        return {
            "rounds": rounds,
            "streams_per_arm": streams_per_arm,
            "token_ms_on": round(on_ms, 3),
            "token_ms_off": round(off_ms, 3),
            "token_ms_on_samples": [round(v, 3) for v in ms_on],
            "token_ms_off_samples": [round(v, 3) for v in ms_off],
            "overhead_frac": round(on_ms / max(1e-9, off_ms) - 1.0, 4),
            "ok": True,
        }
    except Exception as exc:  # the bench must report, not crash
        return {"ok": False, "error": repr(exc)[:300]}
    finally:
        _tracing.set_enabled(was_enabled)
        _tracing.set_local_ingest(None)
        if engine is not None:
            try:
                engine.close()
            except Exception:  # raydp-lint: disable=swallowed-exceptions (probe teardown best-effort)
                pass


def interactive_burst(session, df, n_queries: int) -> dict:
    """p50/p99 latency of ``n_queries`` repeated identical-shape queries on
    a live session — the interactive workload of ROADMAP item 1. One warm-up
    execution compiles + ships the program; the timed loop then measures the
    plan-cache/head-bypass/doorbell warm path end to end. Reports the
    per-query control-plane evidence (plan-cache outcome + RPC round trips
    of the LAST query) alongside the latency quantiles."""
    from raydp_tpu.etl import functions as F

    q = df.select("hour", "dist").filter(F.col("dist") > 0.01)
    q.count()  # compile + ship the program, warm the doorbell sockets
    lat = []
    for _ in range(max(1, n_queries)):
        t0 = time.perf_counter()
        q.count()
        lat.append(time.perf_counter() - t0)
    lat.sort()
    stats = session.last_query_stats
    cache = session._planner.plan_cache_stats()
    probed = cache["hits"] + cache["misses"]
    return {
        "burst_queries": len(lat),
        "burst_p50_ms": round(lat[len(lat) // 2] * 1000, 3),
        "burst_p99_ms": round(
            lat[min(len(lat) - 1, int(len(lat) * 0.99))] * 1000, 3
        ),
        "burst_last_query": {
            "plan_cache": dict(stats.get("plan_cache", {})),
            "rpc": dict(stats.get("rpc", {})),
        },
        # session-lifetime cache counters: the smoke gate asserts hit-rate>0
        "plan_cache_stats": cache,
        "plan_cache_hit_rate": (
            round(cache["hits"] / probed, 4) if probed else 0.0
        ),
    }


def tenant_isolation_probe() -> dict:
    """N concurrent burst drivers on ONE cluster (ROADMAP item 3, the
    multi-tenant bench): tenant *inter* runs an interactive compiled-plan
    burst while tenant *noisy* churns a heavy hash repartition/shuffle
    loop on its own executor. Reports the interactive tenant's p50/p99
    solo vs contended — perf_smoke gates the p99 movement at ≤3x — plus
    ``plan_cache.cross_tenant_hits`` evidence (the noisy tenant running the
    interactive query SHAPE must adopt the shared compiled program).
    Self-contained sessions, separately timed, excluded from every other
    clock."""
    import threading

    import raydp_tpu
    from raydp_tpu import obs, tenancy
    from raydp_tpu.etl import functions as F

    n_burst = int(os.environ.get("BENCH_TENANT_BURST", 150))
    inter = raydp_tpu.init_etl(
        "bench-ten-inter", num_executors=1, executor_cores=1,
        executor_memory="500M",
    )
    noisy = None
    try:
        df_inter = inter.range(100_000, num_partitions=2).with_column(
            "x", F.col("id") * 2
        )
        q = df_inter.filter(F.col("x") % 7 == 0)
        q.count()  # compile + ship the program, warm the doorbell sockets

        def pct(lat, quantile):
            return lat[min(len(lat) - 1, int(len(lat) * quantile))]

        def burst(n, rounds=3):
            """Median-of-rounds p50/p99: a single pass's p99 is one sample
            of the tail on a 2-core box (the r06 interleaved-medians
            lesson) — per-round quantiles with the median across rounds is
            what transfers."""
            p50s, p99s = [], []
            for _ in range(rounds):
                lat = []
                for _ in range(max(1, n)):
                    t0 = time.perf_counter()
                    q.count()
                    lat.append((time.perf_counter() - t0) * 1000.0)
                lat.sort()
                p50s.append(pct(lat, 0.50))
                p99s.append(pct(lat, 0.99))
            p50s.sort()
            p99s.sort()
            return p50s[len(p50s) // 2], p99s[len(p99s) // 2]

        solo = burst(n_burst)

        noisy = raydp_tpu.init_etl(
            "bench-ten-noisy", num_executors=1, executor_cores=1,
            executor_memory="500M",
        )
        df_noisy = noisy.range(150_000, num_partitions=4).with_column(
            "k", F.col("id") % 31
        )
        stop = threading.Event()
        shuffles = [0]

        def churn():
            with tenancy.use_session(noisy):
                while not stop.is_set():
                    df_noisy.repartition(4, "k").count()
                    shuffles[0] += 1

        churner = threading.Thread(target=churn, daemon=True)
        churner.start()
        time.sleep(0.3)  # let the shuffle churn engage before measuring
        contended = burst(n_burst)
        stop.set()
        churner.join(timeout=120)

        # cross-tenant plan-cache evidence: the noisy tenant executes the
        # interactive tenant's exact query shape — same fingerprint, so the
        # shared cache serves inter's compiled program (a cross-tenant hit)
        before = obs.metrics.counter("plan_cache.cross_tenant_hits").value
        with tenancy.use_session(noisy):
            df_same = noisy.range(100_000, num_partitions=2).with_column(
                "x", F.col("id") * 2
            )
            df_same.filter(F.col("x") % 7 == 0).count()
        cross_hits = int(
            obs.metrics.counter("plan_cache.cross_tenant_hits").value - before
        )

        ratio = contended[1] / max(1e-9, solo[1])
        return {
            "burst_queries": n_burst,
            "burst_rounds": 3,
            "solo_p50_ms": round(solo[0], 3),
            "solo_p99_ms": round(solo[1], 3),
            "contended_p50_ms": round(contended[0], 3),
            "contended_p99_ms": round(contended[1], 3),
            "p99_ratio": round(ratio, 3),
            "noisy_shuffles": shuffles[0],
            "cross_tenant_hits": cross_hits,
            "scheduler": tenancy.scheduler().snapshot(),
            # the probe's own gate: bounded interference + proven sharing
            # while the noisy tenant really was shuffling
            "ok": bool(ratio <= 3.0 and cross_hits >= 1 and shuffles[0] >= 1),
        }
    finally:
        if noisy is not None:
            noisy.stop()
        inter.stop()


def obs_overhead_probe() -> dict:
    """Telemetry-on vs telemetry-off cost of the warm compiled-query path,
    plus scrape-endpoint liveness (ISSUE 14; perf_smoke gates both).

    One session, one compiled query shape, interleaved rounds with rotating
    lead (the r06 lesson: alternating A/B medians is what transfers on a
    noisy 2-core box): each round runs the identical burst once with span
    SHIPPING enabled (ring buffer + obs_ingest flushes + TSDB/flight feeds
    — the always-on plane this PR adds) and once with it disabled
    (collector-derived stats stay on in both arms, as they always are; the
    session's executors keep their spawn-time tracing env in both arms, so
    the delta isolates the driver-visible shipping cost). Reports
    median-of-rounds p50s and their quotient.

    Scrape liveness: one real scrape of the head endpoint must parse, carry
    at least one ``tenant``-labeled series and at least one ``serve_``
    series (the serving probe ran earlier in this process, so the driver's
    registry carries the serve plane's counters to the head)."""
    import raydp_tpu
    from raydp_tpu import obs
    from raydp_tpu.etl import functions as F
    from raydp_tpu.obs import tracing as _tracing
    from raydp_tpu.obs.timeseries import parse_prometheus_text, scrape

    n_queries = int(os.environ.get("BENCH_OBS_BURST", 120))
    rounds = int(os.environ.get("BENCH_OBS_ROUNDS", 4))
    session = raydp_tpu.init_etl(
        "bench-obs", num_executors=1, executor_cores=1,
        executor_memory="500M", configs={"obs.scrape_port": "auto"},
    )
    was_enabled = _tracing.enabled()
    try:
        df = session.range(100_000, num_partitions=2).with_column(
            "x", F.col("id") * 3
        )
        q = df.filter(F.col("x") % 5 == 0)
        q.count()  # compile + ship the program, warm the doorbell sockets

        def one_burst() -> float:
            lat = []
            for _ in range(max(1, n_queries)):
                t0 = time.perf_counter()
                q.count()
                lat.append((time.perf_counter() - t0) * 1000.0)
            lat.sort()
            return lat[len(lat) // 2]

        p50_on, p50_off = [], []
        for i in range(max(1, rounds)):
            order = ((True, False), (False, True))[i % 2]  # rotating lead
            for arm_on in order:
                _tracing.set_enabled(arm_on)
                p50 = one_burst()
                (p50_on if arm_on else p50_off).append(p50)
        _tracing.set_enabled(True)
        p50_on.sort()
        p50_off.sort()
        on_ms = p50_on[len(p50_on) // 2]
        off_ms = p50_off[len(p50_off) // 2]
        overhead = on_ms / max(1e-9, off_ms) - 1.0

        # scrape liveness: flush so this driver's registry (incl. the serve
        # probe's counters and this tenant's series) is on the head
        obs.flush()
        scrape_report: dict = {"ok": False}
        addr = session.scrape_addr
        if addr:
            try:
                text = scrape(*addr)
                parsed = parse_prometheus_text(text)
                has_tenant = any(
                    any(k == "tenant" for k, _ in labels)
                    for series in parsed.values() for labels in series
                )
                has_serve = any(
                    name.startswith("raydp_serve_") for name in parsed
                )
                scrape_report = {
                    "ok": bool(parsed),
                    "addr": list(addr),
                    "series": len(parsed),
                    "has_tenant_label": bool(has_tenant),
                    "has_serve_series": bool(has_serve),
                }
            except Exception as exc:  # noqa: BLE001 - the gate reports it
                scrape_report = {"ok": False, "error": repr(exc)[:200]}
        return {
            "burst_queries": n_queries,
            "rounds": rounds,
            "p50_on_ms": round(on_ms, 3),
            "p50_off_ms": round(off_ms, 3),
            "p50_on_samples": [round(v, 3) for v in p50_on],
            "p50_off_samples": [round(v, 3) for v in p50_off],
            "overhead_frac": round(overhead, 4),
            "scrape": scrape_report,
            "ok": bool(scrape_report.get("ok")),
        }
    finally:
        _tracing.set_enabled(was_enabled)
        session.stop()


def fit_profile_probe() -> dict:
    """Step-profiler overhead + live-MFU parity (ISSUE 15; perf_smoke
    gates both).

    Overhead: identical small staged fits (per-step loop forced via
    scan_epochs=False — the path where the per-step instrumentation
    actually sits) with the step profiler ON vs OFF, interleaved rounds
    with rotating lead per the r06 lesson, per-step ms derived from the
    SAME measurement both arms (history epoch_seconds / steps). Reports
    median-of-rounds step p50s.

    Parity: the ON arm's ``fit_stats_`` carries the live FLOPs-per-step
    (XLA cost analysis — the ``estimator.mfu`` gauge's numerator); the
    bench side computes the analytic number for the same MLP through the
    SAME library (``costmodel.mlp_train_flops_per_step``). The ratio must
    land in [0.5, 2.0]: XLA counts the optimizer/elementwise work the
    matmul-only analytic convention deliberately ignores, so exact
    equality is not the contract — same-step-described is."""
    import statistics

    from raydp_tpu.estimator import JaxEstimator
    from raydp_tpu.obs import costmodel, profiler

    rows = int(os.environ.get("BENCH_FIT_PROBE_ROWS", 4096))
    rounds = int(os.environ.get("BENCH_FIT_PROBE_ROUNDS", 3))
    batch = 64
    dims = (8, 64, 64, 1)

    def _mlp():
        import flax.linen as nn

        class _ProbeMLP(nn.Module):
            @nn.compact
            def __call__(self, x):
                x = nn.relu(nn.Dense(dims[1])(x))
                x = nn.relu(nn.Dense(dims[2])(x))
                return nn.Dense(dims[3])(x)

        return _ProbeMLP()

    class _HostDs:
        """Minimal Dataset shim for _stage_host (bench-local: the probe
        measures the train loop, not the ETL exchange)."""

        def __init__(self, feats, labels):
            self._f, self._l = feats, labels
            self.uuid = "fit-profile-probe"
            self.blocks = []

        def to_numpy(self, feature_columns, label_column, feature_dtype,
                     label_dtype):
            return (self._f.astype(feature_dtype),
                    self._l.astype(label_dtype))

    rng = np.random.default_rng(23)
    feats = rng.random((rows, dims[0])).astype(np.float32)
    labels = feats @ rng.random(dims[0]).astype(np.float32)
    ds = _HostDs(feats, labels)

    def make_est():
        return JaxEstimator(
            model=_mlp, optimizer="adam", loss="mse",
            feature_columns=[f"f{i}" for i in range(dims[0])],
            label_column="y", batch_size=batch, num_epochs=2,
            scan_epochs=False, shuffle=True, seed=3,
        )

    was_on = profiler.step_profiler_enabled()
    try:
        est_on, est_off = make_est(), make_est()

        def one_fit(est, arm_on):
            profiler.set_step_profiler(arm_on)
            history = est.fit(ds)
            # the SAME measurement both arms: epoch wall / steps (the off
            # arm has no step histograms to read, by construction)
            steps = max(1, (rows // batch) * len(history))
            total_s = sum(rec["epoch_seconds"] for rec in history)
            return total_s / steps * 1000.0

        one_fit(est_on, True)  # warm both arms: compile + staging cache
        one_fit(est_off, False)
        p50_on, p50_off = [], []
        for i in range(max(1, rounds)):
            order = ((True, False), (False, True))[i % 2]  # rotating lead
            for arm_on in order:
                sample = one_fit(est_on if arm_on else est_off, arm_on)
                (p50_on if arm_on else p50_off).append(sample)
        profiler.set_step_profiler(was_on)

        stats = est_on.fit_stats_
        flops_live = stats.get("flops_per_step")
        flops_analytic = costmodel.mlp_train_flops_per_step(batch, dims)
        ratio = flops_live / flops_analytic if flops_live else None
        parity_ok = ratio is not None and 0.5 <= ratio <= 2.0
        return {
            "rows": rows,
            "rounds": rounds,
            "step_p50_on_ms": round(statistics.median(p50_on), 4),
            "step_p50_off_ms": round(statistics.median(p50_off), 4),
            "step_p50_on_samples": [round(v, 4) for v in p50_on],
            "step_p50_off_samples": [round(v, 4) for v in p50_off],
            "step_phase_seconds": stats.get("step_phase_seconds"),
            "flops_per_step_live": flops_live,
            "flops_per_step_analytic": flops_analytic,
            "flops_ratio": round(ratio, 4) if ratio else None,
            "mfu_live": stats.get("mfu"),
            "model_flops_per_sec": stats.get("model_flops_per_sec"),
            "peak_source": stats.get("peak_source"),
            "mfu_parity_ok": bool(parity_ok),
            "ok": bool(parity_ok),
        }
    except Exception as exc:  # pragma: no cover - must not kill the bench
        # restore the PRE-probe state (an explicit profiler-off run must
        # not be silently re-enabled by a failing probe)
        profiler.set_step_profiler(was_on)
        return {"ok": False, "mfu_parity_ok": False,
                "error": repr(exc)[:300]}


def crosshost_shuffle_probe() -> dict:
    """Cross-host data plane probe (docs/cluster.md "Multi-host topology";
    perf_smoke gates parity + locality hit rate).

    A node agent with its own shm namespace stands in for a second host
    (TCP-only reachability between them). Two arms on the same cluster:
    *cross* spans an executor per host — executor sizing forces the spread
    from live free head CPU, the tests/test_multihost.py trick — while
    *single* packs both executors onto one host. Interleaved rounds with
    rotating lead (the r06 lesson) time the same hash-shuffle groupby on
    both arms; the gate is byte-identical results plus a deterministic
    small fit (seeded, streaming) whose final params must match across
    arms bit-for-bit, with ``rpc.bytes_over_wire`` > 0 proving the wire
    was actually crossed and ``planner.locality_hits`` rate ≥ 0.8 proving
    reduce placement followed the bytes."""
    import statistics

    import jax
    import numpy as np
    import pandas as pd

    import raydp_tpu
    from raydp_tpu import obs, tenancy
    from raydp_tpu.cluster import api as cluster_api
    from raydp_tpu.estimator import JaxEstimator
    from raydp_tpu.etl import functions as F
    from raydp_tpu.exchange import dataframe_to_dataset, dataset_to_dataframe
    from raydp_tpu.models import MLPRegressor

    rows = int(os.environ.get("BENCH_XHOST_ROWS", 120_000))
    rounds = int(os.environ.get("BENCH_XHOST_ROUNDS", 3))

    def _wire_totals():
        merged = cluster_api.dump_metrics()

        def total(name):
            return sum(
                snap.get(name, {}).get("value", 0.0)
                for snap in merged.values()
            )

        return (
            total("rpc.bytes_over_wire"),
            total("rpc.remote_fetches"),
            total("rpc.doorbell_tcp"),
        )

    head_node = next(
        n for n in cluster_api.nodes() if n.agent_addr is None and n.alive
    )
    head_free = cluster_api.available_resources()[head_node.node_id].get(
        "CPU", 0.0
    )
    if head_free < 2:
        return {"ok": False, "note": f"head CPU too small ({head_free})"}
    # cross executors cannot both fit on the head; single executors cannot
    # fit in what the cross arm leaves free there, so they pack onto the
    # (amply sized) simulated host together — each arm's shape is forced,
    # not hoped for, and verified below
    cores_x = int(head_free // 2 + 1)
    cores_s = int(head_free - cores_x + 1)
    agent_info = cluster_api.start_node_agent(
        {"CPU": float(cores_x + 2 * cores_s), "memory": float(2 << 30)},
        shm_ns="xhb",
    )
    agent_node_id = agent_info["node_id"]
    cross = raydp_tpu.init_etl(
        "bench-xhost", num_executors=2, executor_cores=cores_x,
        executor_memory="300M",
    )
    single = None
    try:
        single = raydp_tpu.init_etl(
            "bench-xhost-single", num_executors=2, executor_cores=cores_s,
            executor_memory="300M",
        )
        spans = len({h._record().node_id for h in cross.executors}) == 2
        packed = len({h._record().node_id for h in single.executors}) == 1

        def build_shuffle(session):
            with tenancy.use_session(session):
                src = session.range(rows, num_partitions=8).with_column(
                    "k", F.col("id") % 13
                )
                return dataset_to_dataframe(
                    session, dataframe_to_dataset(src)
                )

        def run_round(session, df):
            with tenancy.use_session(session):
                t0 = time.perf_counter()
                out = df.group_by("k").count().sort("k").collect()
            return time.perf_counter() - t0, out

        df_x, df_s = build_shuffle(cross), build_shuffle(single)
        wire0, fetches0, doorbell0 = _wire_totals()
        hits0 = obs.metrics.counter("planner.locality_hits").value
        misses0 = obs.metrics.counter("planner.locality_misses").value
        _, ref_x = run_round(cross, df_x)  # warm: compile + sockets
        _, ref_s = run_round(single, df_s)
        walls_x, walls_s, parity = [], [], ref_x == ref_s
        for i in range(max(1, rounds)):
            arms = ((cross, df_x), (single, df_s))
            if i % 2:  # rotating lead
                arms = arms[::-1]
            for session, df in arms:
                wall, out = run_round(session, df)
                if session is cross:
                    walls_x.append(wall)
                    parity = parity and out == ref_x
                else:
                    walls_s.append(wall)
                    parity = parity and out == ref_s

        # deterministic small fit on each arm's materialized blocks: the
        # cross arm streams training reads over the wire, and the final
        # params must still match the single-host arm bit-for-bit
        rng = np.random.default_rng(7)
        pdf = pd.DataFrame(
            {
                "a": rng.random(4096).astype(np.float32),
                "b": rng.random(4096).astype(np.float32),
            }
        )
        pdf["y"] = 2 * pdf["a"] + 3 * pdf["b"]

        def fit_leaves(session):
            with tenancy.use_session(session):
                frame = session.from_pandas(pdf, num_partitions=4)
                ds = dataframe_to_dataset(frame.repartition(4))
                est = JaxEstimator(
                    model=MLPRegressor(), optimizer="adam", loss="mse",
                    feature_columns=["a", "b"], label_column="y",
                    batch_size=256, num_epochs=2, learning_rate=1e-3,
                    shuffle=True, seed=0, streaming=True,
                    donate_state=False,
                )
                est.fit(ds)
            params = est.get_model().params
            return [
                np.asarray(leaf).copy()
                for leaf in jax.tree_util.tree_leaves(params)
            ]

        fit_parity = all(
            np.array_equal(a, b)
            for a, b in zip(fit_leaves(cross), fit_leaves(single))
        )

        time.sleep(2.2)  # executor metric flushes are throttled at 2s
        run_round(cross, df_x)  # one settling round flushes the stragglers
        wire1, fetches1, doorbell1 = _wire_totals()
        hits = int(obs.metrics.counter("planner.locality_hits").value - hits0)
        misses = int(
            obs.metrics.counter("planner.locality_misses").value - misses0
        )
        probed = hits + misses
        rate = round(hits / probed, 4) if probed else None
        bytes_over_wire = int(wire1 - wire0)
        return {
            "rows": rows,
            "rounds": rounds,
            "executor_cores_cross": cores_x,
            "executor_cores_single": cores_s,
            "spans_hosts": bool(spans),
            "single_arm_packed": bool(packed),
            "shuffle_wall_s": round(statistics.median(walls_x), 4),
            "singlehost_shuffle_wall_s": round(
                statistics.median(walls_s), 4
            ),
            "shuffle_wall_samples": [round(w, 4) for w in walls_x],
            "singlehost_wall_samples": [round(w, 4) for w in walls_s],
            "bytes_over_wire": bytes_over_wire,
            "remote_fetches": int(fetches1 - fetches0),
            "doorbell_tcp": int(doorbell1 - doorbell0),
            "locality_hits": hits,
            "locality_misses": misses,
            "locality_hit_rate": rate,
            "parity_ok": bool(parity),
            "fit_parity_ok": bool(fit_parity),
            "ok": bool(
                parity and fit_parity and spans and packed
                and bytes_over_wire > 0
                and rate is not None and rate >= 0.8
            ),
        }
    except Exception as exc:  # pragma: no cover - must not kill the bench
        return {"ok": False, "error": repr(exc)[:300]}
    finally:
        if single is not None:
            single.stop()
        cross.stop()
        try:
            cluster_api.remove_node(agent_node_id)
        except Exception:  # raydp-lint: disable=swallowed-exceptions (probe teardown best-effort)
            pass


def _etl_breakdown(stats):
    """Compact, JSON-ready view of the planner's last_query_stats: per-stage
    task counts, dispatch mode, and the server-side read/compute/emit phase
    split, plus the fusion decisions — so a regression in any layer of the
    ETL data plane is attributable from BENCH_r*.json alone."""
    stages = [
        {
            k: (round(v, 4) if isinstance(v, float) else v)
            for k, v in stage.items()
        }
        for stage in stats.get("stages", [])
    ]
    return {
        "seconds": round(stats.get("seconds", 0.0), 4),
        "stages": stages,
        "fusion": stats.get("fusion", []),
        # per-exchange shuffle evidence: blocks written (M indexed vs M×R
        # legacy), bytes, reduce start lag, dispatch mode
        "shuffle": stats.get("shuffle", []),
    }


def streaming_throughput(model, features, ds, trained, batch, epochs,
                         n_samples=None):
    """Steady-state samples/sec of streaming fits, with the pipeline's own
    evidence (VERDICT r4 weak #4): bytes uploaded and producer/consumer idle
    times captured per fit. Two modes: streaming=True (O(block) host AND
    device memory, re-uploads every epoch) and streaming="hybrid" (epoch 1
    streams, later epochs scan the pinned device segments — no host IO).

    Samples are INTERLEAVED across the two modes with rotating lead and the
    MEDIAN reported, exactly like interleaved_fit_vs_pure: the r06
    "hybrid regression" (streaming_hybrid_vs_scan 0.73 after r05's 1.11)
    reproduced as pure measurement noise — this box drifts ±25% between
    identical runs, and one un-interleaved sample per mode hands that drift
    to whichever side ran during a slow stretch. Interleaved 16-epoch
    reruns show hybrid at parity or ahead (151k/148k vs 120k/150k sps)."""
    import statistics

    from raydp_tpu.estimator import JaxEstimator

    if n_samples is None:
        n_samples = int(os.environ.get("BENCH_STREAM_SAMPLES", N_SAMPLES))
    ests = {}
    for key, mode in (("streaming", True), ("streaming_hybrid", "hybrid")):
        est = JaxEstimator(
            model=model, optimizer="adam", loss="mse",
            feature_columns=list(features), label_column="label",
            batch_size=batch, num_epochs=epochs, learning_rate=1e-3,
            shuffle=False, seed=0, donate_state=False, streaming=mode,
        )
        est.fit(ds)  # compile pass
        ests[key] = est
    samples = {key: [] for key in ests}

    def one_fit(key):
        est = ests[key]
        t0 = time.perf_counter()
        est.fit(ds)
        samples[key].append(
            trained / (time.perf_counter() - t0 - est.compile_seconds_)
        )

    keys = list(ests)
    # round UP to a multiple of the mode count so each mode leads equally
    n_samples = -(-max(1, n_samples) // len(keys)) * len(keys)
    warm_probe()
    for i in range(n_samples):
        for j in range(len(keys)):
            one_fit(keys[(i + j) % len(keys)])
    out = {}
    for key, est in ests.items():
        out[f"{key}_sps"] = round(statistics.median(samples[key]), 1)
        out[f"{key}_sps_samples"] = [round(s, 1) for s in samples[key]]
        stats = dict(getattr(est, "stream_stats_", {}))
        for k in ("producer_idle_s", "consumer_idle_s"):
            if k in stats:
                stats[k] = round(stats[k], 3)
        out[f"{key}_pipeline"] = stats
    return out


def eval_throughput(est, ds, n_rows) -> float:
    """Steady-state samples/sec of est.evaluate (one compile pass first):
    the scanned eval path is one dispatch per pass, and this records it —
    eval wall time was a bench blind spot (VERDICT r3 weak #6)."""
    est.evaluate(ds)  # compile + device-stage the eval set
    t0 = time.perf_counter()
    est.evaluate(ds)
    return round(n_rows / (time.perf_counter() - t0), 1)




N_SAMPLES = int(os.environ.get("BENCH_SAMPLES", 3))


def warm_probe():
    """Run a few hundred tiny jitted steps before a timed section so the
    first measured sample isn't paying tunnel/backend warm-up (the tunnel's
    first dispatches after idle are erratically slow). Runs before EVERY
    timed section — minutes of untimed ETL can sit between them and the
    tunnel goes cold again."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones((256, 256))
    f = jax.jit(lambda a: a @ a)
    for _ in range(200):
        x = f(x)
    jax.block_until_ready(x)


def interleaved_fit_vs_pure(est, ds, trained, loop_fn, scan_fn, n_samples=N_SAMPLES):
    """Alternate pure-JAX and framework samples so the tunnel's throughput
    drift (sustained ~300-500k sps with unpredictable multi-x bursts) hits
    ALL sides of the comparison equally; ratios compare medians of co-sampled
    rounds instead of medians taken minutes apart.

    TWO pure-JAX baselines run: the classic per-step jit loop AND a
    whole-epoch ``lax.scan`` with one-shot device staging — the same shape
    the estimator trains with. ``pure_jax_sps`` (the denominator of every
    vs_* ratio) is the STRONGER of the two medians: a ratio against the
    weaker baseline would measure the baseline's dispatch handicap, not
    framework quality (VERDICT r3 weak #1)."""
    import statistics

    warm_probe()
    loops, scans, fits, compiles = [], [], [], []

    def one_fit():
        t0 = time.perf_counter()
        est.fit(ds)
        compiles.append(est.compile_seconds_)
        fits.append(time.perf_counter() - t0 - est.compile_seconds_)

    sides = [lambda: loops.append(loop_fn()), lambda: scans.append(scan_fn()), one_fit]
    # rotate which side goes first: the tunnel often gives the first
    # dispatch burst after idle/warm-up a multi-x boost, and a fixed order
    # would hand that boost to one side systematically. Round the sample
    # count UP to a multiple of len(sides) so every side leads equally —
    # otherwise the extra rounds re-introduce exactly that bias.
    n_samples = -(-n_samples // len(sides)) * len(sides)
    for i in range(n_samples):
        for j in range(len(sides)):
            sides[(i + j) % len(sides)]()
    fit_s = statistics.median(fits)
    loop_sps = statistics.median(loops)
    scan_sps = statistics.median(scans)
    pure_sps = max(loop_sps, scan_sps)
    return {
        "train_s": round(fit_s, 2),
        "compile_s": round(max(compiles), 2),
        "train_only_sps": round(trained / fit_s, 1),
        "pure_jax_loop_sps": round(loop_sps, 1),
        "pure_jax_scan_sps": round(scan_sps, 1),
        "pure_jax_sps": round(pure_sps, 1),
        "train_vs_pure": round((trained / fit_s) / pure_sps, 4),
    }

# the shared feature-container helpers (one array, or a tuple of arrays for
# the mixed-dtype DLRM input): the pure-JAX arms train on the SAME input form
from raydp_tpu.exchange.features import f0 as _b0  # noqa: E402
from raydp_tpu.exchange.features import fmap as _bmap  # noqa: E402


def pure_jax_throughput(model, loss_fn, x, y, batch: int, epochs: int) -> float:
    """Shared pure-JAX baseline: jit step + adam, warm compile, timed epochs.
    Returns samples/sec — the throughput ceiling proxy both workloads compare
    against (one copy so the timing methodology can't drift between them)."""
    import jax
    import jax.numpy as jnp
    import optax

    sample = _bmap(lambda a: jnp.asarray(a[:batch]), x)
    params = jax.jit(model.init)(jax.random.PRNGKey(0), sample)
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, xb, yb):
        def compute(p):
            return loss_fn(model.apply(p, xb), yb)

        loss, grads = jax.value_and_grad(compute)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    params, opt_state, loss = step(
        params, opt_state, sample, jnp.asarray(y[:batch])
    )
    float(loss)
    n_rows = len(_b0(x))
    steps_per_epoch = n_rows // batch
    order = np.arange(n_rows)
    t0 = time.perf_counter()
    count = 0
    for epoch in range(epochs):
        np.random.default_rng(epoch).shuffle(order)
        for s in range(steps_per_epoch):
            idx = order[s * batch : (s + 1) * batch]
            params, opt_state, loss = step(
                params,
                opt_state,
                _bmap(lambda a: jnp.asarray(a[idx]), x),
                jnp.asarray(y[idx]),
            )
            count += 1
            if count % 32 == 0:
                # same queue-depth cap as the estimator (sync_every_steps):
                # unbounded async queues degrade the tunnel ~25x permanently.
                # VALUE fetch, not block_until_ready — the latter can return
                # early on this tunneled plugin (and an early return would
                # both undercount time and defeat the queue cap)
                float(loss)
    float(loss)  # the final fence transitively waits on the whole chain
    return steps_per_epoch * batch * epochs / (time.perf_counter() - t0)


def pure_jax_scan_throughput(model, loss_fn, x, y, batch: int, epochs: int) -> float:
    """The STRONGEST pure-JAX implementation of the same training run: the
    whole dataset staged on device once, each epoch one jitted dispatch that
    gathers shuffled batches device-side and ``lax.scan``s the step over
    them — exactly the one-shot staging the estimator's scan runner uses
    (jax_estimator._build_scan_runner). This is the denominator BASELINE.md's
    "≥80% of pure-JAX" north star has to mean to be honest: a per-step-
    dispatch loop measures the transport, not the chip."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    import optax

    params = jax.jit(model.init)(
        jax.random.PRNGKey(0), _bmap(lambda a: jnp.asarray(a[:batch]), x)
    )
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)
    n_rows = len(_b0(x))
    steps_per_epoch = n_rows // batch
    n_used = steps_per_epoch * batch

    def step(carry, xy):
        params, opt_state = carry
        xb, yb = xy

        def compute(p):
            return loss_fn(model.apply(p, xb), yb)

        loss, grads = jax.value_and_grad(compute)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state), loss

    @jax.jit
    def epoch(params, opt_state, xs, ys, perm):
        xb = _bmap(
            lambda a: a[perm].reshape((steps_per_epoch, batch) + a.shape[1:]),
            xs,
        )
        yb = ys[perm].reshape((steps_per_epoch, batch) + y.shape[1:])
        (params, opt_state), losses = lax.scan(step, (params, opt_state), (xb, yb))
        return params, opt_state, losses.sum()

    # one-shot H2D staging, uncommitted (committed arrays force a slow
    # executor path on some PJRT plugins — mirrors the estimator's staging)
    xs_dev = _bmap(jnp.asarray, x)
    ys_dev = jnp.asarray(y)
    order0 = np.arange(n_rows)
    np.random.default_rng(0).shuffle(order0)
    params, opt_state, loss = epoch(
        params, opt_state, xs_dev, ys_dev, jnp.asarray(order0[:n_used].astype(np.int32))
    )
    float(loss)  # compile + stage outside the clock (value fetch: the only
    # reliable fence on this tunneled plugin — see pure_jax_throughput)
    t0 = time.perf_counter()
    for e in range(epochs):
        order = np.arange(n_rows)
        np.random.default_rng(e).shuffle(order)
        perm = jnp.asarray(order[:n_used].astype(np.int32))
        params, opt_state, loss = epoch(params, opt_state, xs_dev, ys_dev, perm)
    float(loss)
    return n_used * epochs / (time.perf_counter() - t0)

DLRM_VOCABS = [100_000, 10_000, 1_000, 1_000, 100, 100]
DLRM_DENSE = 8


def make_criteo_source(n_rows: int):
    import pandas as pd

    rng = np.random.default_rng(11)
    data = {"label": rng.integers(0, 2, n_rows).astype(np.float32)}
    for i in range(DLRM_DENSE):
        data[f"i{i}"] = rng.integers(0, 1000, n_rows).astype(np.float32)
    for j, vocab in enumerate(DLRM_VOCABS):
        data[f"c{j}"] = rng.integers(0, vocab, n_rows).astype(np.int64)
    return pd.DataFrame(data)


def make_criteo_frame(session, source, parts: int):
    from raydp_tpu.etl import functions as F

    df = session.from_pandas(source, num_partitions=parts)
    for i in range(DLRM_DENSE):
        df = df.with_column(f"i{i}", F.log1p(F.col(f"i{i}")).cast("float32"))
    for j, vocab in enumerate(DLRM_VOCABS):
        # ids stay INTEGER end to end (estimator categorical_columns stages
        # them int32): exact at any vocab size, half the float64 H2D bytes
        df = df.with_column(f"c{j}", F.hash(f"c{j}", vocab).cast("int32"))
    return df


def pandas_taxi_etl(pdf):
    """The fair-comparison ETL arm: the same feature pipeline a
    framework-less user writes with single-process pandas (hour/dow/
    distance), returning the train arrays. Timed by the caller."""
    import pandas as pd  # noqa: F401 - dt accessors

    hour = pdf["pickup_ts"].dt.hour.to_numpy().astype(np.float32)
    dow = pdf["pickup_ts"].dt.dayofweek.to_numpy().astype(np.float32)
    dx = (pdf["dropoff_longitude"] - pdf["pickup_longitude"]).to_numpy()
    dy = (pdf["dropoff_latitude"] - pdf["pickup_latitude"]).to_numpy()
    dist = np.sqrt(dx * dx + dy * dy).astype(np.float32)
    pc = pdf["passenger_count"].to_numpy().astype(np.float32)
    x = np.stack([hour, dow, dist, pc], axis=1)
    y = pdf["fare_amount"].to_numpy().astype(np.float32)
    return x, y


def pandas_criteo_etl(source):
    """Fair-comparison DLRM ETL arm: single-process pandas log1p + hashing
    to (dense float32, ids int32)."""
    import pandas as pd

    dense = np.stack(
        [
            np.log1p(source[f"i{i}"].to_numpy()).astype(np.float32)
            for i in range(DLRM_DENSE)
        ],
        axis=1,
    )
    ids = np.stack(
        [
            (pd.util.hash_array(source[f"c{j}"].to_numpy()) % np.uint64(v))
            .astype(np.int32)
            for j, v in enumerate(DLRM_VOCABS)
        ],
        axis=1,
    )
    y = source["label"].to_numpy().astype(np.float32)
    return (dense, ids), y


def fair_e2e_fields(etl_fn, source, trained, t_boot, t_query, cmp):
    """VERDICT r4 weak #2: the e2e ratio against a ZERO-ETL pure baseline
    answers no question. This arm times the single-process pandas pipeline a
    framework-less user would write, charges the pure-JAX side for it, and
    reports ``e2e_vs_pure_with_etl`` — framework (ETL work + train_s) vs
    (pandas_etl_s + pure train at the measured pure_jax_sps; feature
    CONTENT doesn't change step compute, so the co-sampled throughput
    median is reused rather than re-measured on the pandas arrays).

    Cluster bootstrap is a separate term: the reference's own benchmarks
    run against an ALREADY-STARTED Ray cluster (`ray start --head` precedes
    pytest in its CI, SURVEY §4) and never count it — and the pandas arm's
    interpreter/imports aren't counted either. Both views are reported:
    ``e2e_vs_pure_with_etl`` excludes the one-time boot,
    ``e2e_vs_pure_with_etl_incl_boot`` charges it."""
    t0 = time.perf_counter()
    x, y = etl_fn(source)
    t_pd = time.perf_counter() - t0
    assert len(_b0(x)) == len(y) == len(source)
    pure_e2e = trained / (t_pd + trained / cmp["pure_jax_sps"])
    fw_query = trained / (t_query + cmp["train_s"])
    fw_full = trained / (t_boot + t_query + cmp["train_s"])
    return {
        "pandas_etl_s": round(t_pd, 3),
        "cluster_boot_s": round(t_boot, 3),
        "etl_query_s": round(t_query, 3),
        "e2e_vs_pure_with_etl": round(fw_query / pure_e2e, 4),
        "e2e_vs_pure_with_etl_incl_boot": round(fw_full / pure_e2e, 4),
    }


def bench_dlrm(n_rows: int, batch: int, epochs: int):
    """DLRM/Criteo end-to-end (the BASELINE.json headline workload)."""
    import raydp_tpu
    from raydp_tpu.estimator import JaxEstimator
    from raydp_tpu.exchange import dataframe_to_dataset
    from raydp_tpu.models import DLRM

    dense_cols = [f"i{i}" for i in range(DLRM_DENSE)]
    cat_cols = [f"c{j}" for j in range(len(DLRM_VOCABS))]
    t0 = time.perf_counter()
    source = make_criteo_source(n_rows)
    t_gen = time.perf_counter() - t0
    t0 = time.perf_counter()
    session = raydp_tpu.init_etl(
        "bench-dlrm", num_executors=2, executor_cores=2, executor_memory="1G"
    )
    t_boot = time.perf_counter() - t0
    t0 = time.perf_counter()
    df = make_criteo_frame(session, source, parts=4)
    ds = dataframe_to_dataset(df, _use_owner=True)
    etl_breakdown = _etl_breakdown(session.last_query_stats)
    raydp_tpu.stop_etl(cleanup_data=False, del_obj_holder=False)
    t_query = time.perf_counter() - t0
    t_etl = t_boot + t_query

    model = DLRM(
        vocab_sizes=DLRM_VOCABS, num_dense=DLRM_DENSE, embed_dim=16,
        bottom_mlp=(128, 64), top_mlp=(128, 64),
    )
    # mixed-dtype staging: ids ride a SEPARATE int32 array (exact at any
    # vocab size; float32 would collapse ids past 2^24 and float64 would
    # double the H2D bytes) — VERDICT r4 missing #2
    est = JaxEstimator(
        model=model, optimizer="adam", loss="bce",
        feature_columns=dense_cols + cat_cols,
        categorical_columns=cat_cols,
        label_column="label",
        batch_size=batch, num_epochs=epochs, learning_rate=1e-3, seed=0,
        donate_state=False,
    )
    trained = (n_rows // batch) * batch * epochs

    import jax.numpy as jnp
    import optax

    rng = np.random.default_rng(11)
    # the pure arm trains on the SAME input form: (dense f32, ids i32)
    x = (
        rng.random((n_rows, DLRM_DENSE)).astype(np.float32),
        np.stack(
            [rng.integers(0, v, n_rows) for v in DLRM_VOCABS], axis=1
        ).astype(np.int32),
    )
    y = rng.integers(0, 2, n_rows).astype(np.float32)

    def bce(pred, target):
        return jnp.mean(
            optax.sigmoid_binary_cross_entropy(pred.reshape(target.shape), target)
        )

    cmp = interleaved_fit_vs_pure(
        est, ds, trained,
        lambda: pure_jax_throughput(model, bce, x, y, batch, epochs),
        lambda: pure_jax_scan_throughput(model, bce, x, y, batch, epochs),
    )
    cmp["eval_sps"] = eval_throughput(est, ds, n_rows)
    cmp["etl_breakdown"] = etl_breakdown
    cmp.update(
        fair_e2e_fields(pandas_criteo_etl, source, trained, t_boot, t_query, cmp)
    )
    e2e_sps = trained / (t_etl + cmp["train_s"])
    return {
        "data_gen_s": round(t_gen, 2),
        "etl_s": round(t_etl, 2),
        "e2e_sps": round(e2e_sps, 1),
        "rows": n_rows,
        **cmp,
        # the honest headline per BASELINE.md: END-TO-END (ETL → train)
        # against the pure-JAX loop; the train-only ratio stays in train_vs_pure
        "vs_baseline": round(e2e_sps / cmp["pure_jax_sps"], 4),
    }


_PARALLEL_BENCH_CODE = r"""
import json, os, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from raydp_tpu.parallel import (
    make_mesh, moe_sharded, pipeline_sharded, ring_attention_sharded,
)

N = 8
devices = jax.devices()[:N]
rng = np.random.default_rng(3)
out = {}

def timed(name, fn, *args):
    jax.block_until_ready(fn(*args))  # compile + drain before the clock starts
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        r = fn(*args)
    jax.block_until_ready(r)
    out[name] = round((time.perf_counter() - t0) / reps * 1000, 2)

# ring attention (sp=8): B1 H8 T_total 1024 D64
mesh = make_mesh({"sp": N}, devices)
q = jnp.asarray(rng.standard_normal((1, 8, 1024, 64)), jnp.float32)
ring = jax.jit(lambda a, b, c: ring_attention_sharded(a, b, c, mesh, causal=True))
timed("ring_attention_ms", ring, q, q, q)

# pipeline (pp=8)
pp_mesh = make_mesh({"pp": N}, devices)
W = jnp.asarray(rng.standard_normal((N, 128, 128)) * 0.1, jnp.float32)
x = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32)
pipe = jax.jit(lambda w, t: pipeline_sharded(
    lambda wi, ti: jax.nn.relu(ti @ wi), w, t, pp_mesh, num_microbatches=N))
timed("pipeline_ms", pipe, W, x)

# MoE top-2 (ep=8)
ep_mesh = make_mesh({"ep": N}, devices)
E = jnp.asarray(rng.standard_normal((N, 128, 128)) * 0.1, jnp.float32)
R = jnp.asarray(rng.standard_normal((128, N)) * 0.1, jnp.float32)
tx = jnp.asarray(rng.standard_normal((256, 128)), jnp.float32)
moe = jax.jit(lambda e, r, t: moe_sharded(
    lambda wi, ti: jax.nn.relu(ti @ wi), e, r, t, ep_mesh, top_k=2))
timed("moe_ms", moe, E, R, tx)

print("PARALLEL_JSON:" + json.dumps(out))
"""


def bench_parallel_steps():
    """Step times of the parallel layer (ring attention, pipeline, MoE) on a
    virtual 8-device CPU mesh, via a subprocess so the main process's real
    TPU backend stays untouched. Regressions in parallel/ become visible in
    the driver artifacts (VERDICT r2 item 10). ok:false on any failure —
    never discards the run's other numbers."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    try:
        res = subprocess.run(
            [sys.executable, "-c", _PARALLEL_BENCH_CODE],
            capture_output=True,
            text=True,
            timeout=600,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        for line in res.stdout.splitlines():
            if line.startswith("PARALLEL_JSON:"):
                data = json.loads(line[len("PARALLEL_JSON:"):])
                data["ok"] = True
                data["n_devices"] = 8
                return data
        return {"ok": False, "error": (res.stderr or res.stdout)[-300:]}
    except Exception as e:  # pragma: no cover
        return {"ok": False, "error": repr(e)[:200]}


def validate_flash_compiled():
    """Exactness check of the COMPILED (non-interpret) flash kernel, forward
    and backward, vs the einsum reference — only meaningful on the real chip
    (off-TPU both paths interpret). Returns max abs errors or None off-TPU."""
    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        return None
    from raydp_tpu.ops import flash_attention
    from raydp_tpu.ops.flash_attention import _reference

    rng = np.random.default_rng(5)
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 4, 512, 64)), jnp.float32)
        for _ in range(3)
    )
    g = jnp.asarray(rng.standard_normal((1, 4, 512, 64)), jnp.float32)
    # MXU rounding bound: the reference's own deviation from a highest-
    # precision run measures ~1.4e-2 on these shapes, so 5e-2 is a real
    # exactness gate, not a free pass. Any failure (tolerance OR a Mosaic
    # compile/runtime error) reports ok:false rather than raising — a kernel
    # regression must not discard the run's measured numbers.
    try:
        out, vjp = jax.vjp(
            lambda a, b, c: flash_attention(a, b, c, True, 128, 128, False),
            q, k, v,
        )
        ref, rvjp = jax.vjp(lambda a, b, c: _reference(a, b, c, True), q, k, v)
        fwd_err = float(jnp.max(jnp.abs(out - ref)))
        bwd_err = max(
            float(jnp.max(jnp.abs(x - y))) for x, y in zip(vjp(g), rvjp(g))
        )
    except Exception as e:  # pragma: no cover - hardware-specific failures
        return {"ok": False, "error": repr(e)[:200]}
    return {
        "fwd_max_err": round(fwd_err, 6),
        "bwd_max_err": round(bwd_err, 6),
        "ok": bool(fwd_err < 5e-2 and bwd_err < 5e-2),
    }


# FLOPs accounting + device peaks moved to the library the cluster carries
# (raydp_tpu/obs/costmodel.py, PR 15): bench and the estimator's live
# estimator.mfu gauge import the SAME functions — one accounting, bit-
# identical numbers in both.
from raydp_tpu.obs.costmodel import (  # noqa: E402 - after env setup above
    lm_nonattn_flops_per_step,
    lm_train_flops_per_step,
    mlp_train_flops_per_step,
)


def _device_peak_flops():
    """(device_kind, bf16 peak FLOP/s or None) — thin shim over
    costmodel.device_peak_flops keeping bench's historical TPU-only MFU
    semantics (the nominal-cpu peak is for live dev-box gauges, not for
    BENCH_r* MFU numbers)."""
    from raydp_tpu.obs.costmodel import device_peak_flops

    info = device_peak_flops()
    peak = info["peak"] if info["peak_source"] in ("tpu-table", "env") else None
    return info["kind"], peak


def bench_transformer_lm():
    """MXU-bound single-chip workload: a causal TransformerLM at long
    sequence, flash (pallas) vs einsum attention, reporting tokens/sec and
    an MFU estimate from the model's analytic FLOPs (VERDICT r3 weak #2 —
    every other tracked number is dispatch/ETL-dominated; this one measures
    the chip). Interleaved samples for tunnel-drift fairness. ok:false on
    any failure — never discards the run's other numbers."""
    import statistics

    import jax
    import jax.numpy as jnp
    import optax

    from raydp_tpu.models import TransformerLM

    on_tpu = jax.default_backend() == "tpu"
    T = int(os.environ.get("BENCH_LM_T", 8192 if on_tpu else 256))
    # head_dim 128 (8 heads): fills the MXU's contraction dim — measured
    # ~1.6x faster attention than head_dim 64 on v5e at T=8k
    d_model = int(os.environ.get("BENCH_LM_D", 1024 if on_tpu else 128))
    num_layers = int(os.environ.get("BENCH_LM_LAYERS", 4 if on_tpu else 2))
    num_heads = 8
    vocab = 2048
    # batch 2: measured best MFU on v5e (B=1 0.43, B=2 0.47, B=4 0.45 —
    # bigger batches thrash HBM at T=8k); einsum still fits at B=2
    batch = int(os.environ.get("BENCH_LM_BATCH", 2))
    steps = int(os.environ.get("BENCH_LM_STEPS", 8))
    n_samples = int(os.environ.get("BENCH_LM_SAMPLES", 3))
    flops_step = lm_train_flops_per_step(batch, T, d_model, num_layers, vocab)

    rng = np.random.default_rng(17)
    tok_host = rng.integers(0, vocab, (batch, T + 1), dtype=np.int32)

    def make_runner(impl, **model_kw):
        model = TransformerLM(
            vocab_size=vocab, d_model=d_model, num_heads=num_heads,
            num_layers=num_layers, max_len=T + 1, attn_impl=impl, **model_kw,
        )
        tokens = jnp.asarray(tok_host[:, :-1])
        targets = jnp.asarray(tok_host[:, 1:])
        params = jax.jit(model.init)(jax.random.PRNGKey(0), tokens)
        tx = optax.adam(3e-4)
        opt_state = tx.init(params)

        @jax.jit
        def step(params, opt_state, tok, tgt):
            def compute(p):
                logits = model.apply(p, tok)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, tgt
                ).mean()

            loss, grads = jax.value_and_grad(compute)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        state = {"params": params, "opt": opt_state}

        def run_once():
            p, o = state["params"], state["opt"]
            p, o, loss = step(p, o, tokens, targets)  # warm (compile cached)
            float(loss)  # VALUE fetch: block_until_ready can return EARLY on
            # this tunneled plugin (measured: 0.1ms "block" vs 4.4s of real
            # compute) — a D2H of the final loss is the only reliable fence,
            # and it transitively waits on every step in the chain
            t0 = time.perf_counter()
            for _ in range(steps):
                p, o, loss = step(p, o, tokens, targets)
            float(loss)
            dt = time.perf_counter() - t0
            state["params"], state["opt"] = p, o
            return steps * batch * T / dt

        return run_once

    try:
        warm_probe()
        flash_run = make_runner("flash")
        einsum_run = make_runner("full")
        flash_tps, einsum_tps = [], []
        for i in range(n_samples):
            if i % 2 == 0:
                flash_tps.append(flash_run())
                einsum_tps.append(einsum_run())
            else:
                einsum_tps.append(einsum_run())
                flash_tps.append(flash_run())
        flash_med = statistics.median(flash_tps)
        einsum_med = statistics.median(einsum_tps)
        kind, peak = _device_peak_flops()

        # roofline decomposition (VERDICT r4 weak #3: explain the MFU, don't
        # shrug at it): the same step with attention as identity isolates
        # the non-attention time; the difference is in-model attention time.
        # Attention is VPU-bound (softmax/rescale between MXU calls) at
        # head_dim 128 — its HBM traffic alone would take ~1ms/layer.
        # Each diagnostic runs in its OWN try: one variant failing must not
        # discard the other, nor the already-measured flash/einsum results.
        # TPU-only: off-TPU the decomposition describes nothing (the
        # binding-resource analysis is v5e-specific) and would just slow the
        # CPU smoke job down with two extra compiles.
        roofline = None
        int8_tps = None
        if on_tpu:
            try:
                noattn_tps = make_runner("skip")()
                step_s = batch * T / flash_med
                noattn_flops = lm_nonattn_flops_per_step(
                    batch, T, d_model, num_layers, vocab
                )
                attn_flops = flops_step - noattn_flops
                noattn_s = batch * T / noattn_tps
                attn_s = step_s - noattn_s
                if attn_s > 0.05 * step_s:
                    roofline = {
                        "attn_ms": round(attn_s * 1000, 2),
                        "nonattn_ms": round(noattn_s * 1000, 2),
                        "attn_frac_of_peak": (
                            round(attn_flops / attn_s / peak, 4)
                            if peak
                            else None
                        ),
                        "nonattn_frac_of_peak": (
                            round(noattn_flops / noattn_s / peak, 4)
                            if peak
                            else None
                        ),
                        "binding_resource": (
                            "attention softmax/rescale VPU work at head_dim "
                            "128 (HBM K/V traffic ~0.7ms/layer at 819GB/s; "
                            "matmul stack incl. optimizer/layernorm VPU runs "
                            "near its practical ceiling)"
                        ),
                    }
                else:
                    roofline = {
                        "invalid": (
                            "attention share <= 5% of the step — below the "
                            "single-sample noise floor, decomposition "
                            "withheld"
                        )
                    }
            except Exception as e:  # pragma: no cover - diagnostics only
                roofline = {"error": repr(e)[:160]}
            try:
                int8_tps = make_runner("flash", quantized_mlp=True)()
            except Exception:  # pragma: no cover - diagnostics only
                int8_tps = None
        return {
            "ok": True,
            "seq_len": T,
            "d_model": d_model,
            "num_layers": num_layers,
            "batch": batch,
            "tokens_per_sec": round(flash_med, 1),
            "einsum_tokens_per_sec": round(einsum_med, 1),
            "flash_vs_einsum": round(flash_med / einsum_med, 4),
            "step_ms": round(batch * T / flash_med * 1000, 2),
            "flops_per_step": flops_step,
            # MFU of the HEADLINE (flash) path — not a silent max over
            # variants: the number must describe the same run tokens_per_sec
            # reports
            "model_flops_per_sec": round(flash_med * flops_step / (batch * T), 1),
            "device_kind": kind,
            "peak_flops": peak,
            "mfu": (
                round(flash_med * flops_step / (batch * T) / peak, 4)
                if peak
                else None
            ),
            # int8-MXU forward MLP variant (ops/quantization.int8_matmul,
            # straight-through training): same analytic flops accounting
            "mfu_int8_mlp": (
                round(int8_tps * flops_step / (batch * T) / peak, 4)
                if peak and int8_tps
                else None
            ),
            "roofline": roofline,
        }
    except Exception as e:  # pragma: no cover - hardware-specific failures
        return {"ok": False, "error": repr(e)[:300]}


def _headline_metrics(merged: dict) -> dict:
    """Compact cross-process totals of the obs registry's headline counters
    — the 'where did the work go' numbers next to the trace_path."""

    def total(name):
        value = sum(
            snap.get(name, {}).get("value", 0.0) for snap in merged.values()
        )
        return round(value, 3)

    return {
        "rpc_calls": total("rpc.client.calls"),
        "store_blocks_written": total("store.blocks_written"),
        "store_bytes_written": total("store.bytes_written"),
        "etl_tasks_run": total("etl.tasks_run"),
        "etl_dispatch_batches": total("etl.dispatch_batches"),
        "etl_task_retries": total("etl.task_retries"),
        "actor_restarts": total("cluster.actor_restarts"),
        "estimator_steps": total("estimator.steps"),
        "stream_bytes_uploaded": total("estimator.stream.bytes_uploaded"),
        "input_wait_s": total("estimator.input_wait_s"),
    }


def main():
    # tracing ON for the bench by default (RAYDP_TPU_TRACE=0 opts out): the
    # run's artifact includes a Perfetto timeline of the whole ETL→fit
    # pipeline, and the <2% overhead budget is itself a tracked number
    os.environ.setdefault("RAYDP_TPU_TRACE", "1")
    from raydp_tpu.obs.tracing import reinit_for_process

    reinit_for_process("driver")  # re-read the env in case obs imported early
    _maybe_force_cpu()
    n_rows = int(os.environ.get("BENCH_ROWS", 200_000))
    batch = int(os.environ.get("BENCH_BATCH", 1024))
    # 16 epochs (reference examples train 30): enough training compute that
    # per-fit fixed costs (one H2D round, one history fetch ≈ a tunnel RTT
    # each) don't dominate for ANY side, and the one-time ETL cost in the
    # e2e ratio amortizes the way real runs amortize it
    epochs = int(os.environ.get("BENCH_EPOCHS", 16))

    trained, t_gen, t_etl, cmp = bench_framework(n_rows, batch, epochs)
    framework_sps = trained / (t_etl + cmp["train_s"])

    # free the NYCTaxi session's holder + blocks before the DLRM measurement
    from raydp_tpu.cluster import api as _cluster
    from raydp_tpu.cluster.common import ClusterError
    from raydp_tpu.etl.session import MASTER_ACTOR_SUFFIX

    try:
        _cluster.get_actor(f"bench{MASTER_ACTOR_SUFFIX}").kill()
    except ClusterError:  # raydp-lint: disable=swallowed-exceptions (leftover actor from a prior run; absence is the goal)
        pass  # already gone

    dlrm = bench_dlrm(
        int(os.environ.get("BENCH_DLRM_ROWS", 100_000)),
        int(os.environ.get("BENCH_DLRM_BATCH", 2048)),
        # 30 epochs — the reference notebook's own training length
        # (examples/pytorch_dlrm.ipynb), so training dominates the one-time
        # ETL cost the way real runs amortize it (VERDICT r4 weak #2)
        int(os.environ.get("BENCH_DLRM_EPOCHS", 30)),
    )

    # serving probe (raydp_tpu.serve): closed-loop p50/p99 + sustained rps
    # at a fixed SLO, plus the kill-during-load zero-drop recovery probe —
    # runs on the cluster the earlier sections left initialized, after all
    # training clocks (its wall time touches no other metric)
    serving = serving_probe()

    # decode-native serving probe (docs/serving.md "Decode serving"):
    # multi-client streaming load → decode tokens/sec, TTFT, per-token
    # p99, plus in-process kernel-parity evidence — same placement as the
    # request/response serving probe, after all training clocks
    decode_serving = decode_serving_probe()

    # decode-observatory overhead probe: stream-tracing + SLO-accounting
    # cost per decoded token, tracing on (sample rate 1.0) vs off on an
    # in-process engine, interleaved medians — perf_smoke gates it at ≤5%
    decode_obs = decode_obs_overhead_probe()

    # multi-tenant probe (raydp_tpu.tenancy): interactive burst p50/p99
    # solo vs under a co-tenant's heavy shuffle, plus cross-tenant
    # plan-cache evidence — self-contained sessions on the same cluster,
    # after all training clocks
    tenant_probe = tenant_isolation_probe()

    # telemetry-overhead probe (raydp_tpu.obs v2): identical compiled-query
    # burst with span shipping on vs off (interleaved medians) + one real
    # Prometheus scrape of the head endpoint — after the serving probe so
    # the scrape can prove serve_* series liveness
    obs_probe = obs_overhead_probe()

    # compute-observatory probe (raydp_tpu.obs.profiler/costmodel): step-
    # profiler overhead on the fit step p50 + live-MFU vs analytic parity
    fit_probe = fit_profile_probe()

    # cross-host data plane probe (docs/cluster.md "Multi-host topology"):
    # simulated second host, interleaved cross vs single-host shuffle
    # rounds, bytes-over-wire + locality hit rate, byte-identical parity
    crosshost_probe = crosshost_shuffle_probe()

    # export the whole run's trace (driver + head + executors under the
    # propagated trace ids) and the merged metrics registries — into the
    # gitignored artifacts/ dir, never the repo root
    from raydp_tpu.obs.profiler import artifacts_dir

    trace_path = os.environ.get("BENCH_TRACE_PATH") or os.path.join(
        artifacts_dir(), "bench_trace.json"
    )
    obs_headline: dict = {}
    try:
        from raydp_tpu.cluster import api as _cluster_api

        trace_path = _cluster_api.export_trace(trace_path)
        obs_headline = _headline_metrics(_cluster_api.dump_metrics())
    except Exception as e:  # pragma: no cover - telemetry must not kill bench
        obs_headline = {"error": repr(e)[:160]}
        trace_path = None

    result = {
        "metric": "nyctaxi_mlp_e2e",
        "value": round(framework_sps, 1),
        "trace_path": trace_path,
        "unit": "samples/sec/chip",
        # END-TO-END (ETL → train) vs the pure-JAX loop — BASELINE.md's own
        # wording; the train-only ratio is reported as train_vs_pure
        "vs_baseline": round(framework_sps / cmp["pure_jax_sps"], 4),
        "detail": {
            "data_gen_s": round(t_gen, 2),
            "etl_s": round(t_etl, 2),
            "e2e_sps_incl_etl": round(framework_sps, 1),
            "rows": n_rows,
            "batch": batch,
            "epochs": epochs,
            **cmp,
            "obs_metrics": obs_headline,
            "serving_probe": serving,
            "decode_serving_probe": decode_serving,
            "decode_obs_probe": decode_obs,
            "tenant_isolation_probe": tenant_probe,
            "obs_overhead_probe": obs_probe,
            "fit_profile_probe": fit_probe,
            "crosshost_shuffle_probe": crosshost_probe,
            "dlrm": dlrm,
            "lm": bench_transformer_lm(),
            "parallel_steps": bench_parallel_steps(),
            "flash_compiled": validate_flash_compiled(),
        },
    }
    print(json.dumps(result))  # raydp-lint: disable=print-diagnostics (the JSON result on stdout IS the bench interface; perf_smoke parses it)


if __name__ == "__main__":
    main()
