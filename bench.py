"""End-to-end benchmark: ETL → exchange → train on the NYCTaxi MLP workload.

The reference publishes no numbers (BASELINE.md); the tracked north-star is
samples/sec/chip for the full pipeline vs pure-JAX training throughput on the
same model/data (target ≥ 0.8× — i.e., the framework's data path must not
drag the chip). Prints ONE JSON line.

Runs on whatever jax.devices() provides: the real TPU chip under the driver,
CPU elsewhere (JAX_PLATFORMS=cpu honored despite the image's pre-registered
TPU plugin).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _maybe_force_cpu():
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass


def make_taxi_frame(session, n_rows: int, parts: int):
    """Synthetic NYCTaxi-shaped data + the reference pipeline's feature
    engineering (examples/data_process.py: datetime decomposition, distance)."""
    import pandas as pd

    from raydp_tpu.etl import functions as F

    rng = np.random.default_rng(7)
    base = pd.Timestamp("2020-01-01").value // 10**9
    pickup = base + rng.integers(0, 30 * 24 * 3600, n_rows)
    duration = rng.integers(120, 3600, n_rows)
    pdf = pd.DataFrame(
        {
            "pickup_ts": pd.to_datetime(pickup, unit="s"),
            "passenger_count": rng.integers(1, 6, n_rows).astype(np.int64),
            "pickup_longitude": -74.0 + rng.random(n_rows) * 0.1,
            "pickup_latitude": 40.7 + rng.random(n_rows) * 0.1,
            "dropoff_longitude": -74.0 + rng.random(n_rows) * 0.1,
            "dropoff_latitude": 40.7 + rng.random(n_rows) * 0.1,
            "fare_amount": (2.5 + duration / 240.0 + rng.random(n_rows)).astype(
                np.float64
            ),
        }
    )
    df = session.from_pandas(pdf, num_partitions=parts)
    df = (
        df.with_column("hour", F.hour("pickup_ts").cast("float32"))
        .with_column("dow", F.dayofweek("pickup_ts").cast("float32"))
        .with_column("dx", (F.col("dropoff_longitude") - F.col("pickup_longitude")))
        .with_column("dy", (F.col("dropoff_latitude") - F.col("pickup_latitude")))
        .with_column(
            "dist",
            F.sqrt(F.col("dx") * F.col("dx") + F.col("dy") * F.col("dy")).cast(
                "float32"
            ),
        )
        .with_column("pc", F.col("passenger_count").cast("float32"))
        .with_column("label", F.col("fare_amount").cast("float32"))
        .select("hour", "dow", "dist", "pc", "label")
    )
    return df


FEATURES = ["hour", "dow", "dist", "pc"]


def bench_framework(n_rows: int, batch: int, epochs: int):
    import raydp_tpu
    from raydp_tpu.estimator import JaxEstimator
    from raydp_tpu.exchange import dataframe_to_dataset
    from raydp_tpu.models import MLPRegressor

    t0 = time.perf_counter()
    session = raydp_tpu.init_etl(
        "bench", num_executors=2, executor_cores=2, executor_memory="1G"
    )
    df = make_taxi_frame(session, n_rows, parts=8)
    ds = dataframe_to_dataset(df)
    t_etl = time.perf_counter() - t0

    est = JaxEstimator(
        model=MLPRegressor(),
        optimizer="adam",
        loss="mse",
        feature_columns=FEATURES,
        label_column="label",
        batch_size=batch,
        num_epochs=epochs,
        learning_rate=1e-3,
        shuffle=True,
        seed=0,
    )
    t1 = time.perf_counter()
    est.fit(ds)
    t_train = time.perf_counter() - t1 - est.compile_seconds_
    raydp_tpu.stop_etl()
    trained = (n_rows // batch) * batch * epochs
    return trained, t_etl, t_train, est.compile_seconds_


def bench_pure_jax(n_rows: int, batch: int, epochs: int):
    """Pure-JAX loop on pre-staged numpy — the throughput ceiling proxy."""
    import jax
    import jax.numpy as jnp
    import optax

    from raydp_tpu.models import MLPRegressor

    rng = np.random.default_rng(7)
    x = rng.random((n_rows, len(FEATURES))).astype(np.float32)
    y = rng.random(n_rows).astype(np.float32)

    model = MLPRegressor()
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(x[:batch]))
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, xb, yb):
        def loss_fn(p):
            pred = model.apply(p, xb)
            return jnp.mean((pred.reshape(yb.shape) - yb) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    steps_per_epoch = n_rows // batch
    # warm the compile so both sides measure steady-state throughput
    params, opt_state, _ = step(
        params, opt_state, jnp.asarray(x[:batch]), jnp.asarray(y[:batch])
    )
    jax.block_until_ready(params)
    t0 = time.perf_counter()
    order = np.arange(n_rows)
    for epoch in range(epochs):
        np.random.default_rng(epoch).shuffle(order)
        for s in range(steps_per_epoch):
            idx = order[s * batch : (s + 1) * batch]
            params, opt_state, loss = step(
                params, opt_state, jnp.asarray(x[idx]), jnp.asarray(y[idx])
            )
    jax.block_until_ready(params)
    elapsed = time.perf_counter() - t0
    return steps_per_epoch * batch * epochs, elapsed


def main():
    _maybe_force_cpu()
    n_rows = int(os.environ.get("BENCH_ROWS", 200_000))
    batch = int(os.environ.get("BENCH_BATCH", 1024))
    epochs = int(os.environ.get("BENCH_EPOCHS", 3))

    trained, t_etl, t_train, t_compile = bench_framework(n_rows, batch, epochs)
    framework_sps = trained / (t_etl + t_train)

    base_trained, base_time = bench_pure_jax(n_rows, batch, epochs)
    baseline_sps = base_trained / base_time

    result = {
        "metric": "nyctaxi_mlp_e2e",
        "value": round(framework_sps, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": round((trained / t_train) / baseline_sps, 4),
        "detail": {
            "etl_s": round(t_etl, 2),
            "train_s": round(t_train, 2),
            "compile_s": round(t_compile, 2),
            "train_only_sps": round(trained / t_train, 1),
            "pure_jax_sps": round(baseline_sps, 1),
            "e2e_sps_incl_etl": round(framework_sps, 1),
            "rows": n_rows,
            "batch": batch,
            "epochs": epochs,
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
