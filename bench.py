"""End-to-end benchmark: ETL → exchange → train on the NYCTaxi MLP workload.

The reference publishes no numbers (BASELINE.md); the tracked north-star is
samples/sec/chip for the full pipeline vs pure-JAX training throughput on the
same model/data (target ≥ 0.8× — i.e., the framework's data path must not
drag the chip). Prints ONE JSON line.

Runs on whatever jax.devices() provides: the real TPU chip under the driver,
CPU elsewhere (JAX_PLATFORMS=cpu honored despite the image's pre-registered
TPU plugin).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _maybe_force_cpu():
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass


def make_taxi_source(n_rows: int):
    """Synthesize the NYCTaxi-shaped SOURCE data (stands in for the CSV the
    reference examples read from disk — generation is not ETL and is timed
    separately as data_gen_s)."""
    import pandas as pd

    rng = np.random.default_rng(7)
    base = pd.Timestamp("2020-01-01").value // 10**9
    pickup = base + rng.integers(0, 30 * 24 * 3600, n_rows)
    duration = rng.integers(120, 3600, n_rows)
    return pd.DataFrame(
        {
            "pickup_ts": pd.to_datetime(pickup, unit="s"),
            "passenger_count": rng.integers(1, 6, n_rows).astype(np.int64),
            "pickup_longitude": -74.0 + rng.random(n_rows) * 0.1,
            "pickup_latitude": 40.7 + rng.random(n_rows) * 0.1,
            "dropoff_longitude": -74.0 + rng.random(n_rows) * 0.1,
            "dropoff_latitude": 40.7 + rng.random(n_rows) * 0.1,
            "fare_amount": (2.5 + duration / 240.0 + rng.random(n_rows)).astype(
                np.float64
            ),
        }
    )


def make_taxi_frame(session, pdf, parts: int):
    """The reference pipeline's feature engineering (examples/data_process.py:
    datetime decomposition, distance) on an already-loaded source frame."""
    from raydp_tpu.etl import functions as F

    df = session.from_pandas(pdf, num_partitions=parts)
    df = (
        df.with_column("hour", F.hour("pickup_ts").cast("float32"))
        .with_column("dow", F.dayofweek("pickup_ts").cast("float32"))
        .with_column("dx", (F.col("dropoff_longitude") - F.col("pickup_longitude")))
        .with_column("dy", (F.col("dropoff_latitude") - F.col("pickup_latitude")))
        .with_column(
            "dist",
            F.sqrt(F.col("dx") * F.col("dx") + F.col("dy") * F.col("dy")).cast(
                "float32"
            ),
        )
        .with_column("pc", F.col("passenger_count").cast("float32"))
        .with_column("label", F.col("fare_amount").cast("float32"))
        .select("hour", "dow", "dist", "pc", "label")
    )
    return df


FEATURES = ["hour", "dow", "dist", "pc"]


def bench_framework(n_rows: int, batch: int, epochs: int):
    import raydp_tpu
    from raydp_tpu.estimator import JaxEstimator
    from raydp_tpu.exchange import dataframe_to_dataset
    from raydp_tpu.models import MLPRegressor

    t0 = time.perf_counter()
    pdf = make_taxi_source(n_rows)
    t_gen = time.perf_counter() - t0

    t0 = time.perf_counter()
    session = raydp_tpu.init_etl(
        "bench", num_executors=2, executor_cores=2, executor_memory="1G"
    )
    df = make_taxi_frame(session, pdf, parts=8)
    # ownership transfer + stop: training runs with the ETL engine's CPUs
    # returned (the reference's stop_spark_after_conversion pattern)
    ds = dataframe_to_dataset(df, _use_owner=True)
    raydp_tpu.stop_etl(cleanup_data=False, del_obj_holder=False)
    t_etl = time.perf_counter() - t0

    est = JaxEstimator(
        model=MLPRegressor(),
        optimizer="adam",
        loss="mse",
        feature_columns=FEATURES,
        label_column="label",
        batch_size=batch,
        num_epochs=epochs,
        learning_rate=1e-3,
        shuffle=True,
        seed=0,
        # donation halves device memory for big models but costs ~10-30%
        # dispatch overhead on this plugin; at bench scale memory is not a
        # constraint and the pure-JAX side doesn't donate either
        donate_state=False,
    )
    trained = (n_rows // batch) * batch * epochs
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    x = rng.random((n_rows, len(FEATURES))).astype(np.float32)
    y = rng.random(n_rows).astype(np.float32)

    def mse(pred, target):
        return jnp.mean((pred.reshape(target.shape) - target) ** 2)

    cmp = interleaved_fit_vs_pure(
        est, ds, trained,
        lambda: pure_jax_throughput(MLPRegressor(), mse, x, y, batch, epochs),
    )
    return trained, t_gen, t_etl, cmp




N_SAMPLES = int(os.environ.get("BENCH_SAMPLES", 4))


def warm_probe():
    """Run a few hundred tiny jitted steps before a timed section so the
    first measured sample isn't paying tunnel/backend warm-up (the tunnel's
    first dispatches after idle are erratically slow). Runs before EVERY
    timed section — minutes of untimed ETL can sit between them and the
    tunnel goes cold again."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones((256, 256))
    f = jax.jit(lambda a: a @ a)
    for _ in range(200):
        x = f(x)
    jax.block_until_ready(x)


def interleaved_fit_vs_pure(est, ds, trained, pure_fn, n_samples=N_SAMPLES):
    """Alternate pure-JAX and framework samples so the tunnel's throughput
    drift (sustained ~300-500k sps with unpredictable multi-x bursts) hits
    BOTH sides of the comparison equally; the ratio compares medians of
    co-sampled rounds instead of two medians taken minutes apart."""
    import statistics

    warm_probe()
    pures, fits, compiles = [], [], []

    def one_fit():
        t0 = time.perf_counter()
        est.fit(ds)
        compiles.append(est.compile_seconds_)
        fits.append(time.perf_counter() - t0 - est.compile_seconds_)

    for i in range(n_samples):
        # alternate which side goes first: the tunnel often gives the first
        # dispatch burst after idle/warm-up a multi-x boost, and a fixed
        # order would hand that boost to one side systematically
        if i % 2 == 0:
            pures.append(pure_fn())
            one_fit()
        else:
            one_fit()
            pures.append(pure_fn())
    fit_s = statistics.median(fits)
    pure_sps = statistics.median(pures)
    return {
        "train_s": round(fit_s, 2),
        "compile_s": round(max(compiles), 2),
        "train_only_sps": round(trained / fit_s, 1),
        "pure_jax_sps": round(pure_sps, 1),
        "train_vs_pure": round((trained / fit_s) / pure_sps, 4),
    }

def pure_jax_throughput(model, loss_fn, x, y, batch: int, epochs: int) -> float:
    """Shared pure-JAX baseline: jit step + adam, warm compile, timed epochs.
    Returns samples/sec — the throughput ceiling proxy both workloads compare
    against (one copy so the timing methodology can't drift between them)."""
    import jax
    import jax.numpy as jnp
    import optax

    params = jax.jit(model.init)(jax.random.PRNGKey(0), jnp.asarray(x[:batch]))
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, xb, yb):
        def compute(p):
            return loss_fn(model.apply(p, xb), yb)

        loss, grads = jax.value_and_grad(compute)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    params, opt_state, _ = step(
        params, opt_state, jnp.asarray(x[:batch]), jnp.asarray(y[:batch])
    )
    jax.block_until_ready(params)
    n_rows = len(x)
    steps_per_epoch = n_rows // batch
    order = np.arange(n_rows)
    t0 = time.perf_counter()
    count = 0
    for epoch in range(epochs):
        np.random.default_rng(epoch).shuffle(order)
        for s in range(steps_per_epoch):
            idx = order[s * batch : (s + 1) * batch]
            params, opt_state, _ = step(
                params, opt_state, jnp.asarray(x[idx]), jnp.asarray(y[idx])
            )
            count += 1
            if count % 32 == 0:
                # same queue-depth cap as the estimator (sync_every_steps):
                # unbounded async queues degrade the tunnel ~25x permanently
                jax.block_until_ready(params)
    jax.block_until_ready(params)
    return steps_per_epoch * batch * epochs / (time.perf_counter() - t0)

DLRM_VOCABS = [100_000, 10_000, 1_000, 1_000, 100, 100]
DLRM_DENSE = 8


def make_criteo_source(n_rows: int):
    import pandas as pd

    rng = np.random.default_rng(11)
    data = {"label": rng.integers(0, 2, n_rows).astype(np.float32)}
    for i in range(DLRM_DENSE):
        data[f"i{i}"] = rng.integers(0, 1000, n_rows).astype(np.float32)
    for j, vocab in enumerate(DLRM_VOCABS):
        data[f"c{j}"] = rng.integers(0, vocab, n_rows).astype(np.int64)
    return pd.DataFrame(data)


def make_criteo_frame(session, source, parts: int):
    from raydp_tpu.etl import functions as F

    df = session.from_pandas(source, num_partitions=parts)
    for i in range(DLRM_DENSE):
        df = df.with_column(f"i{i}", F.log1p(F.col(f"i{i}")).cast("float32"))
    for j, vocab in enumerate(DLRM_VOCABS):
        df = df.with_column(f"c{j}", F.hash(f"c{j}", vocab).cast("float32"))
    return df


def bench_dlrm(n_rows: int, batch: int, epochs: int):
    """DLRM/Criteo end-to-end (the BASELINE.json headline workload)."""
    import raydp_tpu
    from raydp_tpu.estimator import JaxEstimator
    from raydp_tpu.exchange import dataframe_to_dataset
    from raydp_tpu.models import DLRM

    features = [f"i{i}" for i in range(DLRM_DENSE)] + [
        f"c{j}" for j in range(len(DLRM_VOCABS))
    ]
    t0 = time.perf_counter()
    source = make_criteo_source(n_rows)
    t_gen = time.perf_counter() - t0
    t0 = time.perf_counter()
    session = raydp_tpu.init_etl(
        "bench-dlrm", num_executors=2, executor_cores=2, executor_memory="1G"
    )
    df = make_criteo_frame(session, source, parts=8)
    ds = dataframe_to_dataset(df, _use_owner=True)
    raydp_tpu.stop_etl(cleanup_data=False, del_obj_holder=False)
    t_etl = time.perf_counter() - t0

    model = DLRM(
        vocab_sizes=DLRM_VOCABS, num_dense=DLRM_DENSE, embed_dim=16,
        bottom_mlp=(128, 64), top_mlp=(128, 64),
    )
    est = JaxEstimator(
        model=model, optimizer="adam", loss="bce",
        feature_columns=features, label_column="label",
        batch_size=batch, num_epochs=epochs, learning_rate=1e-3, seed=0,
        donate_state=False,
    )
    trained = (n_rows // batch) * batch * epochs

    import jax.numpy as jnp
    import optax

    rng = np.random.default_rng(11)
    x = np.concatenate(
        [rng.random((n_rows, DLRM_DENSE)).astype(np.float32)]
        + [
            rng.integers(0, v, (n_rows, 1)).astype(np.float32)
            for v in DLRM_VOCABS
        ],
        axis=1,
    )
    y = rng.integers(0, 2, n_rows).astype(np.float32)

    def bce(pred, target):
        return jnp.mean(
            optax.sigmoid_binary_cross_entropy(pred.reshape(target.shape), target)
        )

    cmp = interleaved_fit_vs_pure(
        est, ds, trained,
        lambda: pure_jax_throughput(model, bce, x, y, batch, epochs),
    )
    e2e_sps = trained / (t_etl + cmp["train_s"])
    return {
        "data_gen_s": round(t_gen, 2),
        "etl_s": round(t_etl, 2),
        "e2e_sps": round(e2e_sps, 1),
        "rows": n_rows,
        **cmp,
        # the honest headline per BASELINE.md: END-TO-END (ETL → train)
        # against the pure-JAX loop; the train-only ratio stays in train_vs_pure
        "vs_baseline": round(e2e_sps / cmp["pure_jax_sps"], 4),
    }


_PARALLEL_BENCH_CODE = r"""
import json, os, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from raydp_tpu.parallel import (
    make_mesh, moe_sharded, pipeline_sharded, ring_attention_sharded,
)

N = 8
devices = jax.devices()[:N]
rng = np.random.default_rng(3)
out = {}

def timed(name, fn, *args):
    jax.block_until_ready(fn(*args))  # compile + drain before the clock starts
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        r = fn(*args)
    jax.block_until_ready(r)
    out[name] = round((time.perf_counter() - t0) / reps * 1000, 2)

# ring attention (sp=8): B1 H8 T_total 1024 D64
mesh = make_mesh({"sp": N}, devices)
q = jnp.asarray(rng.standard_normal((1, 8, 1024, 64)), jnp.float32)
ring = jax.jit(lambda a, b, c: ring_attention_sharded(a, b, c, mesh, causal=True))
timed("ring_attention_ms", ring, q, q, q)

# pipeline (pp=8)
pp_mesh = make_mesh({"pp": N}, devices)
W = jnp.asarray(rng.standard_normal((N, 128, 128)) * 0.1, jnp.float32)
x = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32)
pipe = jax.jit(lambda w, t: pipeline_sharded(
    lambda wi, ti: jax.nn.relu(ti @ wi), w, t, pp_mesh, num_microbatches=N))
timed("pipeline_ms", pipe, W, x)

# MoE top-2 (ep=8)
ep_mesh = make_mesh({"ep": N}, devices)
E = jnp.asarray(rng.standard_normal((N, 128, 128)) * 0.1, jnp.float32)
R = jnp.asarray(rng.standard_normal((128, N)) * 0.1, jnp.float32)
tx = jnp.asarray(rng.standard_normal((256, 128)), jnp.float32)
moe = jax.jit(lambda e, r, t: moe_sharded(
    lambda wi, ti: jax.nn.relu(ti @ wi), e, r, t, ep_mesh, top_k=2))
timed("moe_ms", moe, E, R, tx)

print("PARALLEL_JSON:" + json.dumps(out))
"""


def bench_parallel_steps():
    """Step times of the parallel layer (ring attention, pipeline, MoE) on a
    virtual 8-device CPU mesh, via a subprocess so the main process's real
    TPU backend stays untouched. Regressions in parallel/ become visible in
    the driver artifacts (VERDICT r2 item 10). ok:false on any failure —
    never discards the run's other numbers."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    try:
        res = subprocess.run(
            [sys.executable, "-c", _PARALLEL_BENCH_CODE],
            capture_output=True,
            text=True,
            timeout=600,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        for line in res.stdout.splitlines():
            if line.startswith("PARALLEL_JSON:"):
                data = json.loads(line[len("PARALLEL_JSON:"):])
                data["ok"] = True
                data["n_devices"] = 8
                return data
        return {"ok": False, "error": (res.stderr or res.stdout)[-300:]}
    except Exception as e:  # pragma: no cover
        return {"ok": False, "error": repr(e)[:200]}


def validate_flash_compiled():
    """Exactness check of the COMPILED (non-interpret) flash kernel, forward
    and backward, vs the einsum reference — only meaningful on the real chip
    (off-TPU both paths interpret). Returns max abs errors or None off-TPU."""
    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        return None
    from raydp_tpu.ops import flash_attention
    from raydp_tpu.ops.flash_attention import _reference

    rng = np.random.default_rng(5)
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 4, 512, 64)), jnp.float32)
        for _ in range(3)
    )
    g = jnp.asarray(rng.standard_normal((1, 4, 512, 64)), jnp.float32)
    # MXU rounding bound: the reference's own deviation from a highest-
    # precision run measures ~1.4e-2 on these shapes, so 5e-2 is a real
    # exactness gate, not a free pass. Any failure (tolerance OR a Mosaic
    # compile/runtime error) reports ok:false rather than raising — a kernel
    # regression must not discard the run's measured numbers.
    try:
        out, vjp = jax.vjp(
            lambda a, b, c: flash_attention(a, b, c, True, 128, 128, False),
            q, k, v,
        )
        ref, rvjp = jax.vjp(lambda a, b, c: _reference(a, b, c, True), q, k, v)
        fwd_err = float(jnp.max(jnp.abs(out - ref)))
        bwd_err = max(
            float(jnp.max(jnp.abs(x - y))) for x, y in zip(vjp(g), rvjp(g))
        )
    except Exception as e:  # pragma: no cover - hardware-specific failures
        return {"ok": False, "error": repr(e)[:200]}
    return {
        "fwd_max_err": round(fwd_err, 6),
        "bwd_max_err": round(bwd_err, 6),
        "ok": bool(fwd_err < 5e-2 and bwd_err < 5e-2),
    }


def main():
    _maybe_force_cpu()
    n_rows = int(os.environ.get("BENCH_ROWS", 200_000))
    batch = int(os.environ.get("BENCH_BATCH", 1024))
    epochs = int(os.environ.get("BENCH_EPOCHS", 3))

    trained, t_gen, t_etl, cmp = bench_framework(n_rows, batch, epochs)
    framework_sps = trained / (t_etl + cmp["train_s"])

    # free the NYCTaxi session's holder + blocks before the DLRM measurement
    from raydp_tpu.cluster import api as _cluster
    from raydp_tpu.cluster.common import ClusterError
    from raydp_tpu.etl.session import MASTER_ACTOR_SUFFIX

    try:
        _cluster.get_actor(f"bench{MASTER_ACTOR_SUFFIX}").kill()
    except ClusterError:
        pass  # already gone

    dlrm = bench_dlrm(
        int(os.environ.get("BENCH_DLRM_ROWS", 100_000)),
        int(os.environ.get("BENCH_DLRM_BATCH", 2048)),
        # 4 epochs (reference DLRM notebook trains 30): amortizes the fixed
        # ETL cost over a realistic-but-short training run
        int(os.environ.get("BENCH_DLRM_EPOCHS", 4)),
    )

    result = {
        "metric": "nyctaxi_mlp_e2e",
        "value": round(framework_sps, 1),
        "unit": "samples/sec/chip",
        # END-TO-END (ETL → train) vs the pure-JAX loop — BASELINE.md's own
        # wording; the train-only ratio is reported as train_vs_pure
        "vs_baseline": round(framework_sps / cmp["pure_jax_sps"], 4),
        "detail": {
            "data_gen_s": round(t_gen, 2),
            "etl_s": round(t_etl, 2),
            "e2e_sps_incl_etl": round(framework_sps, 1),
            "rows": n_rows,
            "batch": batch,
            "epochs": epochs,
            **cmp,
            "dlrm": dlrm,
            "parallel_steps": bench_parallel_steps(),
            "flash_compiled": validate_flash_compiled(),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
