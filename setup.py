"""Packaging.

Parity: reference python/setup.py (:44-144) — the wheel bundles the native
layer (there: JVM jars; here: the C++ shared-memory store, built from source
at install time or lazily on first use) and exposes the submit CLI.
"""

import subprocess
from pathlib import Path

from setuptools import Command, find_packages, setup
from setuptools.command.build_py import build_py

ROOT = Path(__file__).parent


class BuildNative(Command):
    """Build the C++ object-store library into the package tree."""

    description = "build native shared-memory store"
    user_options = []

    def initialize_options(self):
        pass

    def finalize_options(self):
        pass

    def run(self):
        native = ROOT / "raydp_tpu" / "store" / "native"
        subprocess.run(["sh", str(native / "build.sh")], check=True)


class BuildPyWithNative(build_py):
    def run(self):
        try:
            self.run_command("build_native")
        except Exception as exc:  # lazy build at first use still works
            print(f"warning: native build skipped ({exc})")
        super().run()


setup(
    name="raydp-tpu",
    version="0.1.0",
    description=(
        "TPU-native single-cluster ETL -> training framework "
        "(distributed Arrow DataFrames + JAX estimators with XLA collectives)"
    ),
    packages=find_packages(include=["raydp_tpu", "raydp_tpu.*"]),
    package_data={"raydp_tpu.store": ["native/*.cpp", "native/build.sh"]},
    python_requires=">=3.10",
    install_requires=[
        "numpy",
        "pyarrow>=4.0.1",
        "pandas",
        "cloudpickle",
        "psutil",
        "jax",
        "flax",
        "optax",
        "orbax-checkpoint",
    ],
    extras_require={
        "torch": ["torch"],
        "tf": ["tensorflow"],
        "xgboost": ["xgboost"],
    },
    entry_points={
        "console_scripts": [
            "raydp-tpu-submit=raydp_tpu.submit:main",
        ]
    },
    cmdclass={"build_native": BuildNative, "build_py": BuildPyWithNative},
)
