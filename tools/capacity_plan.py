"""Decode capacity planner: replicas needed for a target token throughput
at a TTFT/TPOT SLO, planned over the evidence the repo already carries.

Three input planes, strongest-available wins per number:

- the **sentry ledger** (``BENCH_BASELINE.json``): its
  ``decode_tokens_per_sec`` baseline came from the committed
  ``decode_serving_probe`` runs, which deploy :data:`PROBE_REPLICAS`
  replicas — per-replica throughput is the ledger value divided by that;
- the newest **bench snapshot**'s ``decode_serving_probe`` detail
  (``BENCH_r*.json``): observed TTFT and per-token p99 under the probe's
  closed-loop load — the latency evidence the SLO feasibility flags are
  judged against;
- optionally a **live TSDB scrape** (``--scrape host:port``, the
  ``obs.scrape_port`` Prometheus endpoint): current
  ``serve.ttft_ms.p99`` / ``serve.tpot_ms.p99`` / ``serve.decode.goodput``
  series override the snapshot's numbers — plan against what the cluster
  is doing NOW, not what a past bench measured.

A fourth, analytic arm (``obs/costmodel.py``) reports the compute
roofline: tokens/sec per device the probe model could at most decode at
peak FLOP/s — so a plan asking for throughput above ``replicas ×
roofline`` is flagged infeasible regardless of what the probe measured.

The replica count itself is the honest division::

    replicas = ceil(target_tps / (per_replica_tps * utilization))

with ``utilization`` defaulting to :data:`DEFAULT_UTILIZATION` — the probe
measures a saturated closed loop; production admission churn and bursty
arrivals land below that.

``--check`` (the CI gate) verifies the planner against the committed
ledger: planning for exactly the ledger throughput at utilization 1.0 must
ask for exactly the probe's replica count, plans must be monotone in the
target, and SLO feasibility must flag an impossible deadline. Writes the
plan report JSON (``--out``) and exits non-zero on any violation.

Usage:
    python -m tools.capacity_plan --target-tps 2000 \
        --ttft-slo-ms 50 --tpot-slo-ms 20
    python -m tools.capacity_plan --check --out capacity_plan.json
"""
# raydp-lint: disable-file=print-diagnostics (standalone CI tool: its stdout IS the report, there is no obs role to tag)

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import re
import sys
from typing import Any, Dict, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_LEDGER = "BENCH_BASELINE.json"

# decode_serving_probe (bench.py) deploys this many replicas — the ledger's
# decode_tokens_per_sec is the AGGREGATE across them
PROBE_REPLICAS = 2

# the probe's model geometry (bench.py decode_serving_probe): the roofline
# arm prices THIS model; a real deployment passes its own via the flags
PROBE_MODEL = {"d_model": 32, "num_layers": 2, "vocab": 64, "context": 128}

# planned headroom: the probe is a saturated closed loop, production isn't
DEFAULT_UTILIZATION = 0.7


def load_ledger(path: str) -> Dict[str, Any]:
    with open(path) as f:
        ledger = json.load(f)
    return ledger.get("baseline", {})


def newest_bench_detail(repo: str = REPO) -> Optional[Dict[str, Any]]:
    """The newest committed ``BENCH_r*.json``'s ``decode_serving_probe``
    detail, or None when no snapshot carries one (pre-r16 checkouts)."""
    paths = glob.glob(os.path.join(repo, "BENCH_r*.json"))

    def release_n(path: str) -> int:
        match = re.search(r"BENCH_r(\d+)\.json$", path)
        return int(match.group(1)) if match else -1

    for path in sorted(paths, key=release_n, reverse=True):
        try:
            with open(path) as f:
                snap = json.load(f)
        except (OSError, ValueError):  # raydp-lint: disable=swallowed-exceptions (an unreadable/corrupt snapshot just falls through to the next-newest release; the ledger itself is the authoritative input)
            continue
        detail = (snap.get("parsed") or {}).get("detail") or {}
        probe = detail.get("decode_serving_probe")
        if isinstance(probe, dict) and probe.get("tokens"):
            probe = dict(probe)
            probe["source"] = os.path.basename(path)
            return probe
    return None


def scrape_live(addr: str) -> Dict[str, float]:
    """Current decode-plane series from a live scrape endpoint: every
    ``serve.decode.*`` / ``serve.ttft_ms.*`` / ``serve.tpot_ms.*`` /
    ``serve.kv.*`` sample (label-free form), name -> value."""
    from raydp_tpu.obs.timeseries import parse_prometheus_text, scrape

    host, _, port = addr.rpartition(":")
    text = scrape(host or "127.0.0.1", int(port))
    out: Dict[str, float] = {}
    for name, samples in parse_prometheus_text(text).items():
        if not name.startswith(
            ("serve.decode.", "serve.ttft_ms", "serve.tpot_ms", "serve.kv.")
        ):
            continue
        for labels, value in samples.items():
            if not labels:  # the un-labeled (non-tenant) series
                out[name] = value
    return out


def roofline(model: Dict[str, int]) -> Dict[str, Any]:
    """Compute-bound tokens/sec per device for ``model`` at peak FLOP/s —
    None fields when no device/peak is known (jax-free checkouts)."""
    from raydp_tpu.obs.costmodel import lm_decode_flops_per_token

    flops_per_token = lm_decode_flops_per_token(
        model["d_model"], model["num_layers"], model["vocab"],
        model["context"],
    )
    info: Dict[str, Any] = {
        "flops_per_token": flops_per_token,
        "tokens_per_sec_bound": None,
        "peak": None,
        "peak_source": "unknown",
    }
    try:
        from raydp_tpu.obs.costmodel import device_peak_flops

        peak = device_peak_flops()
        info["peak"] = peak.get("peak")
        info["peak_source"] = peak.get("peak_source")
        if peak.get("peak"):
            info["tokens_per_sec_bound"] = peak["peak"] / flops_per_token
    except Exception:  # raydp-lint: disable=swallowed-exceptions (no jax / no device: the roofline arm degrades to unknown, the plan still prices from the ledger)
        pass
    return info


def plan(target_tps: float, per_replica_tps: float,
         utilization: float = DEFAULT_UTILIZATION,
         ttft_slo_ms: Optional[float] = None,
         tpot_slo_ms: Optional[float] = None,
         observed_ttft_ms: Optional[float] = None,
         observed_tpot_p99_ms: Optional[float] = None,
         roofline_tps: Optional[float] = None) -> Dict[str, Any]:
    """One plan: the replica count plus SLO/roofline feasibility flags.
    Feasibility fields are ``None`` (unknown) when either side of the
    comparison is missing — never a silent pass."""
    if per_replica_tps <= 0:
        raise ValueError("per_replica_tps must be positive")
    if not 0 < utilization <= 1.0:
        raise ValueError("utilization must be in (0, 1]")
    effective = per_replica_tps * utilization
    replicas = max(1, math.ceil(target_tps / effective))
    ttft_ok = (
        None if ttft_slo_ms is None or observed_ttft_ms is None
        else observed_ttft_ms <= ttft_slo_ms
    )
    tpot_ok = (
        None if tpot_slo_ms is None or observed_tpot_p99_ms is None
        else observed_tpot_p99_ms <= tpot_slo_ms
    )
    # the analytic ceiling: asking one replica's device for more tokens/sec
    # than the model's FLOPs fit at peak cannot be fixed by measuring again
    compute_ok = (
        None if roofline_tps is None
        else per_replica_tps <= roofline_tps * 1.05  # 5% accounting slack
    )
    return {
        "target_tokens_per_sec": target_tps,
        "per_replica_tokens_per_sec": per_replica_tps,
        "utilization": utilization,
        "replicas": replicas,
        "planned_tokens_per_sec": replicas * effective,
        "ttft_slo_ms": ttft_slo_ms,
        "observed_ttft_ms": observed_ttft_ms,
        "ttft_feasible": ttft_ok,
        "tpot_slo_ms": tpot_slo_ms,
        "observed_tpot_p99_ms": observed_tpot_p99_ms,
        "tpot_feasible": tpot_ok,
        "roofline_tokens_per_sec": roofline_tps,
        "throughput_compute_feasible": compute_ok,
        "feasible": ttft_ok is not False and tpot_ok is not False
        and compute_ok is not False,
    }


def build_report(args: argparse.Namespace) -> Dict[str, Any]:
    baseline = load_ledger(args.ledger)
    decode_stat = baseline.get("decode_tokens_per_sec") or {}
    ledger_tps = float(decode_stat.get("value") or 0.0)
    if ledger_tps <= 0:
        raise SystemExit(
            f"ledger {args.ledger} has no decode_tokens_per_sec baseline "
            "(run bench.py + tools/perf_sentry --write first)"
        )
    per_replica = ledger_tps / PROBE_REPLICAS

    probe = newest_bench_detail()
    observed_ttft = probe.get("ttft_ms") if probe else None
    observed_tpot = probe.get("token_p99_ms") if probe else None

    live: Dict[str, float] = {}
    if args.scrape:
        live = scrape_live(args.scrape)
        observed_ttft = live.get("serve.ttft_ms.p99", observed_ttft)
        observed_tpot = live.get("serve.tpot_ms.p99", observed_tpot)

    roof = roofline(PROBE_MODEL)
    report = {
        "format": "raydp-capacity-plan-v1",
        "ledger": {
            "path": os.path.basename(args.ledger),
            "decode_tokens_per_sec": ledger_tps,
            "probe_replicas": PROBE_REPLICAS,
            "per_replica_tokens_per_sec": per_replica,
        },
        "bench_probe": probe,
        "live": live or None,
        "roofline": roof,
        "plan": plan(
            args.target_tps if args.target_tps is not None else ledger_tps,
            per_replica,
            utilization=args.utilization,
            ttft_slo_ms=args.ttft_slo_ms,
            tpot_slo_ms=args.tpot_slo_ms,
            observed_ttft_ms=observed_ttft,
            observed_tpot_p99_ms=observed_tpot,
            roofline_tps=roof.get("tokens_per_sec_bound"),
        ),
    }
    return report


def run_check(args: argparse.Namespace) -> int:
    """The CI self-check: the planner against its own ledger."""
    report = build_report(args)
    ledger_tps = report["ledger"]["decode_tokens_per_sec"]
    per_replica = report["ledger"]["per_replica_tokens_per_sec"]
    probe = report["bench_probe"] or {}
    failures = []

    # planning for exactly what the probe measured, at the probe's own
    # (saturated) utilization, must ask for exactly the probe's replicas
    identity = plan(ledger_tps, per_replica, utilization=1.0)
    if identity["replicas"] != PROBE_REPLICAS:
        failures.append(
            f"identity plan asked for {identity['replicas']} replicas, "
            f"probe ran {PROBE_REPLICAS}"
        )

    # monotone in the target: more tokens never fewer replicas
    ladder = [
        plan(ledger_tps * mult, per_replica,
             utilization=args.utilization)["replicas"]
        for mult in (0.25, 0.5, 1.0, 2.0, 4.0, 8.0)
    ]
    if ladder != sorted(ladder):
        failures.append(f"replica ladder not monotone: {ladder}")

    # SLO feasibility must actually flag: an impossible per-token deadline
    # (tighter than anything ever measured) must come back infeasible, a
    # generous one feasible — judged against the committed probe evidence
    observed_tpot = probe.get("token_p99_ms")
    if observed_tpot:
        tight = plan(ledger_tps, per_replica, tpot_slo_ms=0.001,
                     observed_tpot_p99_ms=observed_tpot)
        loose = plan(ledger_tps, per_replica,
                     tpot_slo_ms=observed_tpot * 100,
                     observed_tpot_p99_ms=observed_tpot)
        if tight["tpot_feasible"] is not False or tight["feasible"]:
            failures.append("impossible TPOT SLO not flagged infeasible")
        if loose["tpot_feasible"] is not True:
            failures.append("generous TPOT SLO not flagged feasible")
    else:
        failures.append(
            "no committed decode_serving_probe detail (BENCH_r*.json) — "
            "SLO feasibility has no evidence to judge against"
        )

    report["check"] = {"ok": not failures, "failures": failures}
    _write_report(report, args.out)
    print(json.dumps(report["check"], indent=1))
    return 0 if not failures else 1


def _write_report(report: Dict[str, Any], out: Optional[str]) -> None:
    if not out:
        return
    with open(out, "w") as f:
        json.dump(report, f, indent=1, default=str)
    print(f"wrote {out}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--target-tps", type=float, default=None,
                        help="target aggregate tokens/sec "
                        "(default: the ledger baseline)")
    parser.add_argument("--ttft-slo-ms", type=float, default=None)
    parser.add_argument("--tpot-slo-ms", type=float, default=None)
    parser.add_argument("--utilization", type=float,
                        default=DEFAULT_UTILIZATION,
                        help="planned per-replica utilization (0, 1]")
    parser.add_argument("--ledger",
                        default=os.path.join(REPO, DEFAULT_LEDGER))
    parser.add_argument("--scrape", default=None, metavar="HOST:PORT",
                        help="live TSDB scrape endpoint; overrides the "
                        "bench snapshot's observed TTFT/TPOT")
    parser.add_argument("--out", default=None,
                        help="write the full plan report JSON here")
    parser.add_argument("--check", action="store_true",
                        help="CI self-check against the committed ledger")
    args = parser.parse_args(argv)

    if args.check:
        return run_check(args)
    report = build_report(args)
    _write_report(report, args.out)
    p = report["plan"]
    print(
        f"target {p['target_tokens_per_sec']:.1f} tok/s at "
        f"{p['utilization']:.0%} utilization -> {p['replicas']} replicas "
        f"({p['per_replica_tokens_per_sec']:.1f} tok/s each, plans to "
        f"{p['planned_tokens_per_sec']:.1f})"
    )
    for side in ("ttft", "tpot"):
        slo = p[f"{side}_slo_ms"]
        if slo is None:
            continue
        observed = p[f"observed_{side}_ms" if side == "ttft"
                     else "observed_tpot_p99_ms"]
        verdict = p[f"{side}_feasible"]
        print(
            f"{side} SLO {slo:.2f} ms vs observed "
            f"{observed if observed is not None else '?'} ms: "
            f"{'ok' if verdict else 'INFEASIBLE' if verdict is False else 'unknown'}"
        )
    if p["throughput_compute_feasible"] is False:
        print(
            f"INFEASIBLE: per-replica demand exceeds the compute roofline "
            f"({p['roofline_tokens_per_sec']:.1f} tok/s/device)"
        )
    return 0 if p["feasible"] else 1


if __name__ == "__main__":
    sys.exit(main())
