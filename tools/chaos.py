"""Chaos-mode test harness: make executor failure boring.

SIGKILLs executors mid-shuffle, mid-compiled-dispatch, and mid-streaming-fit
(intentional kills — the head unregisters the victim's blocks, so the loss
is REAL, unlike a restartable crash whose shm survives) and asserts that

- every query/fit completes with a result byte-identical to an unkilled run
  (lineage recovery, docs/fault_tolerance.md),
- ``lineage.reexecuted_tasks`` stays within one map round per production
  level per kill, and
- the PR 4/5 runtime sanitizers (``RAYDP_TPU_SANITIZE=donation,lockdep,
  leaks-strict``) stay clean — the leak/lockdep auditors double as a
  recovery-correctness oracle. Gated: ZERO leaked shm segments / spill
  files at the strict shutdown audit, ZERO stranded threads per scenario;
  fd counts ride the report as advisory (the sanitize design's own stance
  on raw fd deltas).

The per-host block service (store/block_service.py) splits the scenarios
into two ownership tiers:

- the three lineage scenarios run with ``store.block_service=false`` (the
  PR 8 arm — executor-owned blocks, loss is real, recovery re-executes);
- ``executor_kill_with_service`` kills an executor mid-shuffle with the
  service ON and gates ``lineage.reexecuted_tasks == 0`` — executor death
  must lose ZERO blocks;
- ``service_kill_lineage_fallback`` SIGKILLs the block service itself
  mid-query: real loss of every service-owned block, recovered via lineage
  byte-identically.

Usage::

    RAYDP_TPU_SANITIZE=donation,lockdep,leaks-strict \
        python -m tools.chaos --quick --seed 7 --json chaos_report.json

The serving plane adds a third tier: ``replica_kill_during_load`` SIGKILLs
a model replica mid-request-stream and gates ZERO dropped requests plus
responses byte-identical to an unkilled run (the deployment pins a single
batch bucket — XLA numerics are bit-stable per shape — and re-admitted
requests are pure re-computation; docs/serving.md "Failover").

The multi-tenant plane adds a fourth tier: ``tenant_kill_isolation`` runs
two tenants on one cluster, SIGKILLs tenant A's block-holding executor
mid-query, and gates tenant B's CONCURRENT query byte-identical with zero
lineage re-execution charged to it — one tenant's failure (and recovery)
must never leak into another's blocks, plans, or results
(docs/multitenancy.md).

The cross-host plane adds a fifth tier: ``host_death`` boots a second
SIMULATED host (a node agent with its own shm namespace — TCP-only
reachability, docs/cluster.md "Multi-host topology"), spans a session
across both, and SIGKILLs every actor sharing the simulated host
mid-query. The gate is two-tier: the dead host's executor-owned blocks
come back via lineage, while the surviving host's service-owned blocks
never re-execute — byte-identical either way
(docs/fault_tolerance.md kill matrix).

``--quick`` runs the CI slice (mid-shuffle + mid-fit lineage kills, both
block-service tiers, the tenant-isolation kill, the replica kill, and the
simulated host death); without it the full scenario list runs (adds the
compiled-dispatch kill and the elasticity round-trip). ``--seed``
makes victim/timing selection deterministic (unseeded runs keep the fixed
legacy choices). Exit code is non-zero when any query went unrecovered or
any sanitizer finding surfaced. The same scenario bodies are reused by
``tests/test_chaos.py`` / ``tests/test_block_service.py`` via the
importable helpers below.
"""
# raydp-lint: disable-file=print-diagnostics (standalone CI tool: its stdout IS the report, there is no obs role to tag)

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time
from typing import Callable, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


# ---------------------------------------------------------------------------
# primitives (importable by tests/test_chaos.py)
# ---------------------------------------------------------------------------

# --seed: deterministic victim/timing selection. Unseeded (None) keeps the
# legacy fixed choices (index 0, exact delays) so existing runs reproduce.
RNG: Optional[random.Random] = None


def set_seed(seed: Optional[int]) -> None:
    global RNG
    RNG = None if seed is None else random.Random(seed)


def pick_index(n: int) -> int:
    """Seeded victim index over an n-executor pool (0 when unseeded)."""
    if RNG is None or n <= 1:
        return 0
    return RNG.randrange(n)


def jittered(delay_s: float) -> float:
    """Seeded timing jitter for delayed kills (exact delay when unseeded):
    the kill lands in a DIFFERENT query window per seed, so repeated seeded
    runs sweep the race surface deterministically."""
    if RNG is None:
        return delay_s
    return delay_s * (0.5 + RNG.random())


def kill_executor(session, handle=None, index: int = 0):
    """SIGKILL one executor with NO restart — the real-loss chaos primitive:
    the head unregisters (tombstones) its blocks and unlinks their segments,
    so any surviving reference must come back through lineage recovery. The
    dead owner is recorded in the store so stale head-bypass locations
    fast-path to OwnerDiedError. Returns the victim handle."""
    from raydp_tpu.store import object_store as store

    victim = handle if handle is not None else session.executors[index]
    victim.kill(no_restart=True)
    store.note_owner_dead(victim._actor_id)
    return victim


def delayed_kill(
    session, delay_s: float, index: Optional[int] = 0
) -> threading.Thread:
    """Arm a timer thread that SIGKILLs an executor mid-whatever-is-running
    (``index=None`` = seeded victim pick at fire time; the delay rides the
    seeded jitter either way). Join it after the workload completes."""

    def _fire():
        time.sleep(jittered(delay_s))
        try:
            victim = index
            if victim is None:
                victim = pick_index(len(session.executors))
            kill_executor(session, index=victim)
        except Exception:  # raydp-lint: disable=swallowed-exceptions (chaos timer: the victim may already be gone, racing scenario teardown)
            pass

    thread = threading.Thread(target=_fire, name="chaos-killer", daemon=True)
    thread.start()
    return thread


def kill_service(session):
    """SIGKILL the session's block service with NO restart — the real-loss
    primitive of the SERVICE tier: the head tombstones and unlinks every
    service-owned block, so surviving references must come back through
    lineage re-execution. Returns the (dead) service handle."""
    from raydp_tpu.store import object_store as store

    victim = session.block_service
    if victim is None:
        raise RuntimeError("session has no block service (conf off?)")
    victim.kill(no_restart=True)
    store.note_owner_dead(victim._actor_id)
    return victim


def serve_request_stream(dep, x, n_requests: int, n_clients: int = 4):
    """Drive a FIXED single-row request list through a serving deployment
    from ``n_clients`` closed-loop client threads. Returns (results,
    errors) with results positionally stable, so two runs of the same
    stream are comparable row-for-row. Shared by the chaos scenario, the
    bench kill probe, and tests — one body, no drift."""

    results = [None] * n_requests
    errors: List[str] = []
    rows = len(x)

    def client(lo: int, hi: int) -> None:
        for i in range(lo, hi):
            try:
                results[i] = dep.predict(x[i % rows : i % rows + 1])
            except Exception as exc:  # noqa: BLE001 - the gate counts these
                errors.append(repr(exc)[:200])

    share = max(1, n_requests // n_clients)
    workers = [
        threading.Thread(
            target=client,
            args=(k * share, min(n_requests, (k + 1) * share)),
        )
        for k in range(n_clients)
    ]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    return results, errors


def serve_kill_probe(
    dep,
    x,
    n_requests: int = 160,
    kill_delay_s: float = 0.05,
    pick_victim: Optional[Callable[[], int]] = None,
    heal_timeout_s: float = 20.0,
) -> dict:
    """The serving zero-drop contract, as one reusable probe: run a fixed
    request stream clean, re-run it with a replica SIGKILLed mid-stream
    (``pick_victim`` chooses the index at fire time; seeded scenarios pass
    ``pick_index``), and gate ZERO dropped requests + responses
    byte-identical to the clean run + the pool healed back to target.
    The deployment should pin a single batch bucket so every dispatch is
    one fixed shape (docs/serving.md: XLA numerics are bit-stable per
    shape, which is what makes cross-run byte-identity honest)."""
    import numpy as np

    from raydp_tpu import obs

    target = dep.replica_count()
    clean, clean_errors = serve_request_stream(dep, x, n_requests)
    dropped_before = obs.metrics.counter("serve.dropped_requests").value

    def _fire():
        time.sleep(jittered(kill_delay_s))
        try:
            idx = pick_victim() if pick_victim is not None else 0
            dep._handles[idx].kill(no_restart=True)
        except Exception:  # raydp-lint: disable=swallowed-exceptions (probe timer: replica may already be gone, racing teardown)
            pass

    killer = threading.Thread(target=_fire, daemon=True)
    killer.start()
    killed, killed_errors = serve_request_stream(dep, x, n_requests)
    killer.join()
    dropped = int(
        obs.metrics.counter("serve.dropped_requests").value - dropped_before
    )
    identical = (
        not clean_errors
        and not killed_errors
        and all(r is not None for r in clean)
        and all(r is not None for r in killed)
        and all(np.array_equal(a, b) for a, b in zip(clean, killed))
    )
    deadline = time.monotonic() + heal_timeout_s
    while dep.replica_count() < target and time.monotonic() < deadline:
        time.sleep(0.05)
    healed = dep.replica_count() == target
    return {
        "requests": n_requests,
        "dropped": dropped,
        "byte_identical": bool(identical),
        "pool_healed": bool(healed),
        "ok": bool(identical and dropped == 0 and healed),
        "errors": (clean_errors + killed_errors)[:3],
    }


def block_owner_executor(session, ds):
    """An executor handle that owns at least one of the dataset's blocks
    (killing it makes the loss real), or None."""
    from raydp_tpu.store import object_store as store

    owners = {store.owner_of(b) for b in ds.blocks}
    for handle in session.executors:
        if handle._actor_id in owners:
            return handle
    return None


def lineage_counters() -> dict:
    from raydp_tpu import obs

    return {
        "reexecuted_tasks": int(
            obs.metrics.counter("lineage.reexecuted_tasks").value
        ),
        "recovered_blocks": int(
            obs.metrics.counter("lineage.recovered_blocks").value
        ),
    }


def sanitizer_report() -> dict:
    """The current process's leak inventory (the cluster-level audit runs at
    shutdown; chaos scenarios also sample between kills)."""
    from raydp_tpu import sanitize

    if not sanitize.leaks_enabled():
        return {"enabled": False}
    report = sanitize.leak_report()
    return {
        "enabled": True,
        "shm": len(report["shm"]),
        "spill": len(report["spill"]),
        "fds": report["fds"],
        "threads": report["threads"],
    }


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def _fresh_session(name: str, executors: int = 2, configs: Optional[dict] = None):
    import raydp_tpu

    return raydp_tpu.init_etl(
        name, num_executors=executors, executor_cores=1,
        executor_memory="300M", configs=configs,
    )


# the PR 8 arm: executor-OWNED blocks, so an executor SIGKILL is real loss
# and lineage recovery is the only way back. The three lineage scenarios
# pin this conf so the fallback tier stays proven now that the block
# service (default ON) makes executor death lose nothing on the common path.
LINEAGE_ARM = {"store.block_service": "false"}


def scenario_mid_shuffle(rows: int = 120_000) -> dict:
    """Kill a block-holding executor between a shuffle's map and reduce
    rounds (deterministic window: the map outputs exist, the reduce hasn't
    read them) and while a full query is in flight (timed kill)."""
    import raydp_tpu
    from raydp_tpu.etl import functions as F
    from raydp_tpu.exchange import dataframe_to_dataset, dataset_to_dataframe

    session = _fresh_session("chaos-shuffle", configs=LINEAGE_ARM)
    try:
        # deterministic half: a shuffle whose SOURCE blocks are executor-
        # owned loses real data when the owner dies — the map round must
        # lineage-recover them mid-exchange
        src = session.range(rows, num_partitions=8).with_column(
            "k", F.col("id") % 13
        )
        ds = dataframe_to_dataset(src)
        df = dataset_to_dataframe(session, ds)
        clean = df.group_by("k").count().sort("k").collect()
        before = lineage_counters()
        victim = block_owner_executor(session, ds)
        kill_executor(session, handle=victim)
        time.sleep(0.3)
        chaos = df.group_by("k").count().sort("k").collect()
        session.request_total_executors(2)  # restore the pool

        # racing half: a timed kill lands wherever it lands (map dispatch,
        # between rounds, reduce read) — every window must hold; seeded
        # runs sweep the window deterministically (victim + delay jitter)
        killer = delayed_kill(session, 0.05, index=None)
        chaos2 = df.group_by("k").count().sort("k").collect()
        killer.join()
        session.request_total_executors(2)

        after = lineage_counters()
        reexecuted = after["reexecuted_tasks"] - before["reexecuted_tasks"]
        identical = chaos == clean and chaos2 == clean
        # bound: ≤ one map round (8 tasks) per production LEVEL per kill —
        # recovery transitively re-materializes the lost blocks' source
        # inputs too (one extra level here), and this scenario injects TWO
        # kills: 8 × 2 levels × 2 kills
        bound = 32
        return {
            "name": "mid_shuffle_kill",
            "ok": bool(identical and reexecuted >= 1),
            "byte_identical": bool(identical),
            "reexecuted_tasks": reexecuted,
            "reexecution_bound": bound,
            "within_bound": reexecuted <= bound,
        }
    finally:
        raydp_tpu.stop_etl()


def scenario_mid_compiled(rows: int = 50_000) -> dict:
    """Kill the owner of a materialized dataset's blocks, then run a
    COMPILED (plan-cache + run_plan) query over it: the compiled dispatch's
    lost-block fallback must lineage-recover and re-run."""
    import raydp_tpu
    from raydp_tpu.etl import functions as F
    from raydp_tpu.exchange import dataframe_to_dataset, dataset_to_dataframe

    session = _fresh_session("chaos-compiled", configs=LINEAGE_ARM)
    try:
        src = session.range(rows, num_partitions=4).with_column(
            "x", F.col("id") * 3
        )
        ds = dataframe_to_dataset(src)
        df = dataset_to_dataframe(session, ds)
        clean = df.filter(F.col("x") % 2 == 0).count()
        before = lineage_counters()

        victim = block_owner_executor(session, ds)
        assert victim is not None
        kill_executor(session, handle=victim)
        time.sleep(0.5)
        chaos = df.filter(F.col("x") % 2 == 0).count()
        session.request_total_executors(2)

        after = lineage_counters()
        reexecuted = after["reexecuted_tasks"] - before["reexecuted_tasks"]
        return {
            "name": "mid_compiled_dispatch_kill",
            "ok": chaos == clean and reexecuted >= 1,
            "byte_identical": chaos == clean,
            "reexecuted_tasks": reexecuted,
            "reexecution_bound": 4,
            "within_bound": reexecuted <= 4,
        }
    finally:
        raydp_tpu.stop_etl()


def scenario_mid_fit(rows: int = 2048) -> dict:
    """SIGKILL the executor owning the training blocks mid-streaming-fit:
    the streaming iterator's block reads lineage-recover and the fit's
    final params must be byte-identical to an unkilled run."""
    import numpy as np
    import pandas as pd

    import raydp_tpu
    from raydp_tpu.exchange import dataframe_to_dataset

    def _fit(session, ds, kill_after_steps: Optional[int]) -> dict:
        import jax

        from raydp_tpu.estimator import JaxEstimator
        from raydp_tpu.models import MLPRegressor

        est = JaxEstimator(
            model=MLPRegressor(),
            optimizer="adam",
            loss="mse",
            feature_columns=["a", "b"],
            label_column="y",
            batch_size=256,
            num_epochs=2,
            learning_rate=1e-3,
            shuffle=True,
            seed=0,
            streaming=True,
            donate_state=False,
        )
        if kill_after_steps is not None:
            # lose the blocks FOR REAL before the stream starts: the fit's
            # block reads then recover through lineage WHILE it runs (a
            # timed kill on data this small usually lands after the last
            # read and proves nothing)
            victim = block_owner_executor(session, ds)
            if victim is not None:
                kill_executor(session, handle=victim)
                time.sleep(0.3)
        est.fit(ds)
        params = est.get_model().params
        leaves = jax.tree_util.tree_leaves(params)
        return {
            "digest": [float(np.asarray(leaf).sum()) for leaf in leaves],
            "raw": [np.asarray(leaf).copy() for leaf in leaves],
        }

    session = _fresh_session("chaos-fit", configs=LINEAGE_ARM)
    try:
        rng = np.random.default_rng(3)
        pdf = pd.DataFrame(
            {
                "a": rng.random(rows).astype(np.float32),
                "b": rng.random(rows).astype(np.float32),
            }
        )
        pdf["y"] = 2 * pdf["a"] + 3 * pdf["b"]
        df = session.from_pandas(pdf, num_partitions=4)
        # materialize through the EXECUTORS so the blocks are executor-owned
        # (a from_pandas source is driver-owned — killing an executor would
        # lose nothing); repartition keeps the rows bit-identical
        ds = dataframe_to_dataset(df.repartition(4))
        clean = _fit(session, ds, kill_after_steps=None)
        before = lineage_counters()
        chaos = _fit(session, ds, kill_after_steps=1)
        session.request_total_executors(2)
        after = lineage_counters()
        identical = all(
            np.array_equal(c, k) for c, k in zip(clean["raw"], chaos["raw"])
        )
        reexecuted = after["reexecuted_tasks"] - before["reexecuted_tasks"]
        return {
            "name": "mid_streaming_fit_kill",
            "ok": bool(identical and reexecuted >= 1),
            "byte_identical": bool(identical),
            "reexecuted_tasks": reexecuted,
            "reexecution_bound": 8,
            "within_bound": reexecuted <= 8,
        }
    finally:
        raydp_tpu.stop_etl()


def scenario_elasticity() -> dict:
    """Scale-out under sustained queue depth (warm zygote fork — timed),
    then scale-in of a block-holding executor: no query may lose data."""
    import raydp_tpu
    from raydp_tpu.etl import functions as F
    from raydp_tpu.exchange import dataframe_to_dataset

    session = _fresh_session("chaos-elastic", executors=1)
    try:
        t0 = time.perf_counter()
        session.request_total_executors(2)
        scale_out_s = time.perf_counter() - t0
        # materialize AFTER the scale-out so both executors produce blocks;
        # kill_executors takes victims from the pool's tail — the new
        # executor. With the block service ON (default here) the data
        # survives because the victims never owned it (zero reown RPCs);
        # the conf-off reown-to-master arm is pinned in test_block_service.
        df = session.range(20_000, num_partitions=4).with_column(
            "v", F.col("id") + 1
        )
        ds = dataframe_to_dataset(df)
        expected = ds.count()
        session.kill_executors(1, min_keep=1)
        survived = ds.to_arrow().num_rows == expected
        ok = survived and len(session.executors) >= 1
        return {
            "name": "elastic_round_trip",
            "ok": bool(ok),
            "scale_out_s": round(scale_out_s, 3),
            "scale_out_warm": scale_out_s < 1.0,
            "data_survived_scale_in": bool(survived),
        }
    finally:
        raydp_tpu.stop_etl()


def scenario_executor_kill_with_service(rows: int = 120_000) -> dict:
    """The block-service tier's headline contract: with
    ``store.block_service`` ON (the default), an executor SIGKILL
    mid-shuffle loses ZERO blocks — the per-host service owns every
    completed block, reads keep hitting shm, and the query completes
    byte-identical with ``lineage.reexecuted_tasks == 0`` (in-flight tasks
    on the victim re-dispatch via the ordinary retry ladder, which is not
    lineage re-execution). Both halves of the mid-shuffle scenario run:
    a deterministic kill between queries and a timed kill mid-query."""
    import raydp_tpu
    from raydp_tpu.etl import functions as F
    from raydp_tpu.exchange import dataframe_to_dataset, dataset_to_dataframe

    session = _fresh_session("chaos-exec-svc")
    try:
        src = session.range(rows, num_partitions=8).with_column(
            "k", F.col("id") % 13
        )
        ds = dataframe_to_dataset(src)
        # ownership sanity: the blocks belong to the SERVICE, not any
        # executor — otherwise this scenario would silently test the
        # lineage arm (block_owner_executor finds executor-owned blocks)
        service_owned = block_owner_executor(session, ds) is None
        df = dataset_to_dataframe(session, ds)
        clean = df.group_by("k").count().sort("k").collect()
        before = lineage_counters()

        kill_executor(session, index=pick_index(len(session.executors)))
        time.sleep(0.3)
        chaos = df.group_by("k").count().sort("k").collect()
        session.request_total_executors(2)

        killer = delayed_kill(session, 0.05, index=None)
        chaos2 = df.group_by("k").count().sort("k").collect()
        killer.join()
        session.request_total_executors(2)

        after = lineage_counters()
        reexecuted = after["reexecuted_tasks"] - before["reexecuted_tasks"]
        identical = chaos == clean and chaos2 == clean
        return {
            "name": "executor_kill_with_service",
            "ok": bool(identical and service_owned and reexecuted == 0),
            "byte_identical": bool(identical),
            "service_owned": bool(service_owned),
            # THE gate: executor death must cost zero re-executed tasks
            "reexecuted_tasks": reexecuted,
            "reexecution_bound": 0,
            "within_bound": reexecuted == 0,
        }
    finally:
        raydp_tpu.stop_etl()


def scenario_service_kill_lineage_fallback(rows: int = 60_000) -> dict:
    """The fallback tier: SIGKILL the block SERVICE itself (no restart —
    the head tombstones and unlinks every service-owned block, real loss)
    both between queries and mid-query, and assert lineage re-execution
    brings the results back byte-identical under the strict sanitizers."""
    import raydp_tpu
    from raydp_tpu.etl import functions as F
    from raydp_tpu.exchange import dataframe_to_dataset, dataset_to_dataframe

    session = _fresh_session("chaos-svc-kill")
    try:
        src = session.range(rows, num_partitions=8).with_column(
            "k", F.col("id") % 13
        )
        ds = dataframe_to_dataset(src)
        df = dataset_to_dataframe(session, ds)
        clean = df.group_by("k").count().sort("k").collect()
        before = lineage_counters()

        # deterministic half: the service (and all its blocks) die between
        # queries — the next query's reads surface OwnerDiedError and
        # lineage re-executes the producing tasks on the live executors
        kill_service(session)
        time.sleep(0.3)
        chaos = df.group_by("k").count().sort("k").collect()

        # racing half: a fresh session (the dead service released its
        # name), service killed WHILE a query is in flight
        raydp_tpu.stop_etl()
        session = _fresh_session("chaos-svc-kill-2")
        src2 = session.range(rows, num_partitions=8).with_column(
            "k", F.col("id") % 13
        )
        ds2 = dataframe_to_dataset(src2)
        df2 = dataset_to_dataframe(session, ds2)
        clean2 = df2.group_by("k").count().sort("k").collect()

        def _fire():
            time.sleep(jittered(0.05))
            try:
                kill_service(session)
            except Exception:  # raydp-lint: disable=swallowed-exceptions (chaos timer: racing scenario teardown)
                pass

        killer = threading.Thread(target=_fire, daemon=True)
        killer.start()
        chaos2 = df2.group_by("k").count().sort("k").collect()
        killer.join()

        after = lineage_counters()
        reexecuted = after["reexecuted_tasks"] - before["reexecuted_tasks"]
        identical = chaos == clean and chaos2 == clean2
        # bound: the deterministic half re-executes ≤ one map round + one
        # source level (8 × 2); the racing half may or may not lose blocks
        # depending on where the kill lands — same allowance
        bound = 32
        return {
            "name": "service_kill_lineage_fallback",
            "ok": bool(identical and reexecuted >= 1),
            "byte_identical": bool(identical),
            "reexecuted_tasks": reexecuted,
            "reexecution_bound": bound,
            "within_bound": reexecuted <= bound,
        }
    finally:
        raydp_tpu.stop_etl()


def scenario_replica_kill_during_load(n_requests: int = 240) -> dict:
    """The serving plane's zero-drop contract (docs/serving.md): SIGKILL a
    model replica MID-REQUEST-STREAM and gate on

    - ZERO dropped requests (every client future resolves — in-flight
      batches on the dead replica are re-admitted and re-served, pure
      inference being idempotent), and
    - responses BYTE-IDENTICAL to an unkilled run of the same stream. The
      deployment pins a single batch bucket so every dispatch is one fixed
      shape: XLA numerics are bit-stable per shape regardless of batch
      composition, which makes cross-run byte-identity an honest gate.

    The controller must also heal the pool back to target. Runs under the
    same strict sanitizers as every scenario; replica/batcher/controller
    threads and sockets all land in the shutdown leak audit."""
    import numpy as np
    import pandas as pd

    import raydp_tpu
    from raydp_tpu import obs, serve
    from raydp_tpu.estimator import JaxEstimator
    from raydp_tpu.models import MLPRegressor

    import tempfile

    ckpt_dir = tempfile.mkdtemp(prefix="chaos-serve-ckpt-")
    rng = np.random.default_rng(5)
    rows = 1024
    pdf = pd.DataFrame(
        {
            "a": rng.random(rows).astype(np.float32),
            "b": rng.random(rows).astype(np.float32),
        }
    )
    pdf["y"] = 2 * pdf["a"] + 3 * pdf["b"]
    session = _fresh_session("chaos-serve")
    dep = None
    try:
        est = JaxEstimator(
            model=MLPRegressor(hidden=(8,)), optimizer="adam", loss="mse",
            feature_columns=["a", "b"], label_column="y", batch_size=64,
            num_epochs=1, seed=0, checkpoint_dir=ckpt_dir,
            donate_state=False,
        )
        est.fit_on_etl(session.from_pandas(pdf, num_partitions=2))
        x = pdf[["a", "b"]].to_numpy(np.float32)
        dep = serve.deploy(
            est, replicas=2, example=x[0],
            conf={
                "serve.max_batch_size": 16,
                "serve.batch_buckets": [16],  # deterministic shapes
                "serve.autoscale.tick_s": 0.1,
            },
        )

        probe = serve_kill_probe(
            dep, x, n_requests=n_requests,
            pick_victim=lambda: pick_index(dep.replica_count()),
        )
        return {
            "name": "replica_kill_during_load",
            "ok": probe["ok"],
            "byte_identical": probe["byte_identical"],
            "dropped_requests": probe["dropped"],
            "requeued_requests": int(
                obs.metrics.counter("serve.requeued_requests").value
            ),
            "replica_replacements": int(
                obs.metrics.counter("serve.replica_replacements").value
            ),
            "pool_healed": probe["pool_healed"],
            "errors": probe["errors"],
        }
    finally:
        if dep is not None:
            dep.close()
        raydp_tpu.stop_etl()


def scenario_tenant_kill_isolation(rows: int = 60_000) -> dict:
    """The multi-tenant blast-radius contract (docs/multitenancy.md): two
    tenants share ONE cluster; tenant A's block-holding executor is
    SIGKILLed mid-query (A runs the lineage arm, so the loss is real) while
    tenant B's query runs CONCURRENTLY. Gates:

    - B's result is BYTE-IDENTICAL to its clean run with
      ``lineage.reexecuted_tasks == 0`` charged to B's query (the per-query
      ``last_query_stats['recovery']`` record — A's recovery must never
      touch B's blocks or plans);
    - A (the victim tenant) recovers as usual: byte-identical via lineage
      with ≥1 re-executed task.

    Runs under the same strict sanitizers as every scenario."""
    import raydp_tpu
    from raydp_tpu import tenancy
    from raydp_tpu.etl import functions as F
    from raydp_tpu.exchange import dataframe_to_dataset, dataset_to_dataframe

    # tenant A on the lineage arm (executor-owned blocks = real loss);
    # tenant B on the defaults. Both attach to one cluster as named tenants.
    session_a = _fresh_session("chaos-ten-a", configs=dict(LINEAGE_ARM))
    session_b = None
    try:
        session_b = raydp_tpu.init_etl(
            "chaos-ten-b", num_executors=1, executor_cores=1,
            executor_memory="300M",
        )
        src_a = session_a.range(rows, num_partitions=8).with_column(
            "k", F.col("id") % 13
        )
        ds_a = dataframe_to_dataset(src_a)
        df_a = dataset_to_dataframe(session_a, ds_a)
        src_b = session_b.range(rows // 2, num_partitions=4).with_column(
            "k", F.col("id") % 7
        )
        ds_b = dataframe_to_dataset(src_b)
        df_b = dataset_to_dataframe(session_b, ds_b)
        with tenancy.use_session(session_a):
            clean_a = df_a.group_by("k").count().sort("k").collect()
        with tenancy.use_session(session_b):
            clean_b = df_b.group_by("k").count().sort("k").collect()

        victim = block_owner_executor(session_a, ds_a)
        kill_executor(session_a, handle=victim)
        time.sleep(0.3)

        b_out: dict = {}

        def run_b():
            with tenancy.use_session(session_b):
                try:
                    b_out["result"] = (
                        df_b.group_by("k").count().sort("k").collect()
                    )
                    b_out["recovery"] = dict(
                        session_b.last_query_stats.get("recovery", {})
                    )
                except Exception as exc:  # noqa: BLE001 - the gate reports it
                    b_out["error"] = repr(exc)[:300]

        thread_b = threading.Thread(target=run_b, name="tenant-b-query")
        thread_b.start()
        before = lineage_counters()
        with tenancy.use_session(session_a):
            chaos_a = df_a.group_by("k").count().sort("k").collect()
        after = lineage_counters()
        thread_b.join(timeout=120)
        session_a.request_total_executors(2)

        a_reexecuted = after["reexecuted_tasks"] - before["reexecuted_tasks"]
        a_identical = chaos_a == clean_a
        b_identical = b_out.get("result") == clean_b
        b_reexecuted = int(
            b_out.get("recovery", {}).get("reexecuted_tasks", -1)
        )
        ok = bool(
            a_identical
            and a_reexecuted >= 1
            and b_identical
            and b_reexecuted == 0
            and "error" not in b_out
        )
        entry = {
            "name": "tenant_kill_isolation",
            "ok": ok,
            "victim_tenant_byte_identical": bool(a_identical),
            "victim_tenant_reexecuted_tasks": a_reexecuted,
            "other_tenant_byte_identical": bool(b_identical),
            # THE gate: the co-tenant's concurrent query pays ZERO recovery
            "other_tenant_reexecuted_tasks": b_reexecuted,
        }
        if "error" in b_out:
            entry["other_tenant_error"] = b_out["error"]
        return entry
    finally:
        if session_b is not None:
            session_b.stop()
        session_a.stop()


def scenario_replica_kill_during_decode(
    n_streams: int = 4, max_new: int = 12
) -> dict:
    """The streaming edition of the serving zero-drop contract
    (docs/serving.md "Decode serving"): SIGKILL a replica while
    autoregressive decode streams are in flight on its continuous-batching
    engine. The deployment heals, and each interrupted stream re-prefills
    prompt + already-emitted tokens on a survivor. Gates:

    - every stream completes its FULL token budget with zero errors (no
      stream dropped, no token emitted twice or lost);
    - tokens IDENTICAL to an unkilled run of the same prompts — an honest
      gate because greedy argmax over f32 logits at fixed compiled shapes
      plus the decode-step ≡ prefill kernel bit-parity (gated in
      tests/test_flash_decode.py) makes the re-prefilled continuation
      produce exactly the tokens the dead replica would have;
    - the pool heals back to target replicas.

    The dead replica's paged KV arena is one shm block owned by the
    replica actor: the head unregisters a killed owner's blocks and
    unlinks their segments, so the strict shutdown leak audit below also
    gates that a SIGKILL mid-decode strands no KV memory."""
    import tempfile

    import numpy as np

    import jax
    import jax.numpy as jnp

    import raydp_tpu
    from raydp_tpu import obs, serve
    from raydp_tpu.estimator import JaxEstimator
    from raydp_tpu.models import TransformerLM

    vocab = 64
    model = TransformerLM(
        vocab_size=vocab, d_model=32, num_heads=2, num_layers=2,
        max_len=256, attn_impl="flash", dtype=jnp.float32,
    )
    ckpt_dir = tempfile.mkdtemp(prefix="chaos-decode-ckpt-")
    est = JaxEstimator(model=model, checkpoint_dir=ckpt_dir)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    est._save_checkpoint(params, 0, {})

    session = _fresh_session("chaos-decode")
    dep = None
    scenario_t0 = time.time()
    try:
        dep = serve.deploy(
            model=model, checkpoint_dir=ckpt_dir, replicas=2,
            conf={
                "serve.decode.enabled": True,
                "serve.decode.capacity_tokens": 128,
                "serve.decode.page_tokens": 32,
                "serve.decode.max_seqs": n_streams,
                "serve.decode.max_new_tokens": max_new,
            },
        )
        target = dep.replica_count()
        rng = np.random.default_rng(29)
        prompts = [
            [int(t) for t in rng.integers(0, vocab, rng.integers(3, 10))]
            for _ in range(n_streams)
        ]

        # clean reference run: per-stream tokens depend only on the
        # stream's own prompt (batch-composition independence, gated in
        # tests/test_decode_serve.py), so a sequential clean run is a
        # valid reference for the concurrent killed run
        clean = [dep.generate(p, max_new, timeout=180) for p in prompts]

        failovers_before = obs.metrics.counter(
            "serve.decode.failovers"
        ).value
        partial: List[list] = [[] for _ in range(n_streams)]
        results: List[Optional[list]] = [None] * n_streams
        errors: List[str] = []

        def client(i: int):
            try:
                for tok in dep.stream(prompts[i], max_new, timeout=180):
                    partial[i].append(int(tok))
                results[i] = list(partial[i])
            except Exception as exc:  # noqa: BLE001 - the gate reports it
                errors.append(repr(exc)[:200])

        threads = [
            threading.Thread(target=client, args=(i,), name=f"decode-{i}")
            for i in range(n_streams)
        ]
        for t in threads:
            t.start()

        def _fire():
            # deterministically MID-stream: wait until every stream has
            # ~2 tokens out (far from its budget of max_new), then kill —
            # a wall-clock delay can land after the streams finish, which
            # would make the whole gate vacuous
            deadline = time.monotonic() + 120
            while (sum(len(p) for p in partial) < 2 * n_streams
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            try:
                idx = pick_index(dep.replica_count())
                dep._handles[idx].kill(no_restart=True)
            except Exception:  # raydp-lint: disable=swallowed-exceptions (chaos timer: replica may already be gone, racing teardown)
                pass

        killer = threading.Thread(target=_fire, daemon=True)
        killer.start()
        for t in threads:
            t.join(timeout=240)
        killer.join()

        failovers = int(
            obs.metrics.counter("serve.decode.failovers").value
            - failovers_before
        )
        complete = all(
            r is not None and len(r) == max_new for r in results
        )
        identical = complete and not errors and all(
            r == c for r, c in zip(results, clean)
        )
        deadline = time.monotonic() + 20.0
        while dep.replica_count() < target and time.monotonic() < deadline:
            time.sleep(0.05)
        healed = dep.replica_count() == target
        # crash-dossier decode section (obs/recorder.py): the SIGKILL made
        # the head write a dossier for the victim, and it must carry the
        # decode observatory's section — the victim's last in-flight-stream
        # state note and/or its serve.decode.*/serve.kv.* gauges. Gated
        # only when a dossier dir is configured (the chaos runner always
        # sets one); the write is async with the death event, so poll.
        dossier_dir = os.environ.get("RAYDP_TPU_DOSSIER_DIR", "")
        dossier_decode = None
        if dossier_dir:
            from raydp_tpu.obs.recorder import list_dossiers

            dossier_decode = False
            poll_deadline = time.monotonic() + 10.0
            while not dossier_decode and time.monotonic() < poll_deadline:
                for path in reversed(list_dossiers(dossier_dir)):
                    try:
                        with open(path) as f:
                            doc = json.load(f)
                    except (OSError, ValueError):  # raydp-lint: disable=swallowed-exceptions (a dossier mid-write by the head is retried on the next poll tick; the 10s deadline turns persistent unreadability into a gate failure)
                        continue
                    if float(doc.get("ts") or 0) < scenario_t0:
                        continue
                    if doc.get("decode"):
                        dossier_decode = True
                        break
                else:
                    time.sleep(0.25)
        return {
            "name": "replica_kill_during_decode",
            # failovers >= 1: the kill provably interrupted live streams —
            # token identity with zero failovers would gate nothing
            "ok": bool(identical and healed and failovers >= 1
                       and dossier_decode is not False),
            "streams": n_streams,
            "tokens_per_stream": max_new,
            "token_identical": bool(identical),
            "streams_complete": bool(complete),
            "failovers": failovers,
            "pool_healed": bool(healed),
            "dossier_decode_section": dossier_decode,
            "errors": errors[:3],
        }
    finally:
        if dep is not None:
            dep.close()
        raydp_tpu.stop_etl()


def scenario_host_death(rows: int = 60_000) -> dict:
    """SIGKILL every actor sharing one SIMULATED host mid-query (the
    cross-host plane's whole-box failure: docs/fault_tolerance.md kill
    matrix, docs/cluster.md "Multi-host topology").

    A node agent with its own shm namespace stands in for the second host:
    its executors' blocks live in a namespace nobody else can map, so its
    death is REAL loss (no service serves that namespace) and recovery must
    come through lineage on the surviving host. The head host's blocks are
    SERVICE-owned; the service survives, so they must come back without a
    single re-executed task. Gate: post-death query byte-identical, lineage
    re-execution ≥ 1 (the dead host) and bounded, service ownership intact
    (the surviving host)."""
    import raydp_tpu
    from raydp_tpu.cluster import api as cluster_api
    from raydp_tpu.etl import functions as F
    from raydp_tpu.exchange import dataframe_to_dataset, dataset_to_dataframe
    from raydp_tpu.store import object_store as store

    if not cluster_api.is_initialized():
        cluster_api.init(num_cpus=4, memory=4 << 30)
    # size executors from LIVE free head resources so the second one cannot
    # fit on the head node and must land on the simulated host (the sizing
    # trick tests/test_multihost.py uses)
    head_node = next(
        n for n in cluster_api.nodes() if n.agent_addr is None and n.alive
    )
    head_free = cluster_api.available_resources()[head_node.node_id].get(
        "CPU", 0.0
    )
    cores = int(head_free // 2 + 1)
    info = cluster_api.start_node_agent(
        {"CPU": float(cores), "memory": float(1 << 30)}, shm_ns="chd"
    )
    agent_node_id = info["node_id"]
    session = raydp_tpu.init_etl(
        "chaos-host-death", num_executors=2, executor_cores=cores,
        executor_memory="300M",
    )
    try:
        victims = [
            h for h in session.executors
            if h._record().node_id == agent_node_id
        ]
        spans_hosts = 0 < len(victims) < len(session.executors)
        src = session.range(rows, num_partitions=8).with_column(
            "k", F.col("id") % 13
        )
        ds = dataframe_to_dataset(src)
        svc_id = (
            session.block_service._actor_id
            if session.block_service is not None else None
        )
        svc_owned = [b for b in ds.blocks if store.owner_of(b) == svc_id]
        victim_ids = {h._actor_id for h in victims}
        host_owned = [b for b in ds.blocks if store.owner_of(b) in victim_ids]
        df = dataset_to_dataframe(session, ds)
        clean = df.group_by("k").count().sort("k").collect()
        before = lineage_counters()

        # deterministic half: the whole simulated host dies between queries
        # — every actor sharing it (its executors; the namespace hosts no
        # block service) SIGKILLed, its executor-owned blocks tombstoned —
        # and the next query must lineage-recover them on the survivor
        for victim in victims:
            kill_executor(session, handle=victim)
        time.sleep(0.3)
        chaos = df.group_by("k").count().sort("k").collect()
        session.request_total_executors(2)

        # racing half: the host dies again WHILE a query is in flight (the
        # respawned executor cannot fit on the head — the sizing above —
        # so it landed back on the simulated host)
        victims2 = [
            h for h in session.executors
            if h._record().node_id == agent_node_id
        ]

        def _fire():
            time.sleep(jittered(0.05))
            for victim in victims2:
                try:
                    kill_executor(session, handle=victim)
                except Exception:  # raydp-lint: disable=swallowed-exceptions (chaos timer: racing scenario teardown)
                    pass

        killer = threading.Thread(
            target=_fire, name="chaos-host-killer", daemon=True
        )
        killer.start()
        chaos2 = df.group_by("k").count().sort("k").collect()
        killer.join()
        session.request_total_executors(2)

        # the surviving host's service-owned blocks never left the service
        service_intact = all(store.owner_of(b) == svc_id for b in svc_owned)

        after = lineage_counters()
        reexecuted = after["reexecuted_tasks"] - before["reexecuted_tasks"]
        identical = chaos == clean and chaos2 == clean
        # bound: ≤ one 8-task map round + one transitive source level per
        # host-death event (two events); the surviving host's service-owned
        # blocks must contribute zero
        bound = 32
        return {
            "name": "host_death",
            "ok": bool(
                identical and spans_hosts and reexecuted >= 1
                and service_intact and len(svc_owned) >= 1
                and len(host_owned) >= 1
            ),
            "byte_identical": bool(identical),
            "spans_hosts": bool(spans_hosts),
            "dead_host_blocks": len(host_owned),
            "surviving_service_blocks": len(svc_owned),
            "surviving_service_intact": bool(service_intact),
            "reexecuted_tasks": reexecuted,
            "reexecution_bound": bound,
            "within_bound": reexecuted <= bound,
        }
    finally:
        raydp_tpu.stop_etl()
        try:
            cluster_api.remove_node(agent_node_id)
        except Exception:  # raydp-lint: disable=swallowed-exceptions (teardown: node may already be gone at cluster shutdown)
            pass


QUICK = (
    scenario_mid_shuffle,
    scenario_mid_fit,
    scenario_executor_kill_with_service,
    scenario_service_kill_lineage_fallback,
    scenario_tenant_kill_isolation,
    scenario_replica_kill_during_load,
    scenario_replica_kill_during_decode,
    scenario_host_death,
)
FULL = (
    scenario_mid_shuffle,
    scenario_mid_compiled,
    scenario_mid_fit,
    scenario_executor_kill_with_service,
    scenario_service_kill_lineage_fallback,
    scenario_tenant_kill_isolation,
    scenario_elasticity,
    scenario_replica_kill_during_load,
    scenario_replica_kill_during_decode,
    scenario_host_death,
)


def run(scenarios) -> dict:
    from raydp_tpu import sanitize
    from raydp_tpu.cluster import api as cluster_api

    results: List[dict] = []
    for scenario in scenarios:
        name = scenario.__name__
        t0 = time.perf_counter()
        try:
            entry = scenario()
        except Exception as exc:  # one scenario must not hide the rest
            entry = {"name": name, "ok": False, "error": repr(exc)[:500]}
        entry["seconds"] = round(time.perf_counter() - t0, 2)
        # leak inventory AFTER the scenario's session stopped. GATED here:
        # stranded THREADS (stable zero — a recovery that leaks a producer
        # or reaper thread shows up immediately). Reported only: fds (the
        # sanitize design treats raw fd counts as advisory — library
        # internals open them unpredictably) and shm/spill (driver-owned
        # blocks legitimately live until cluster shutdown, where the
        # leaks-strict audit below is exact and fatal).
        entry["sanitizer"] = sanitizer_report()
        if entry["sanitizer"].get("threads"):
            entry["ok"] = False
            entry["sanitizer_fail"] = (
                f"{entry['sanitizer']['threads']} stranded thread(s)"
            )
        results.append(entry)
        print(f"[chaos] {entry.get('name', name)}: "
              f"{'OK' if entry.get('ok') else 'FAILED'} "
              f"({entry['seconds']}s)")
    # final teardown audit: leaks-strict raises on any leaked segment —
    # the recovery-correctness oracle the harness exists to arm
    sanitizer_findings = 0
    try:
        cluster_api.shutdown()
    except sanitize.LeakError as exc:
        sanitizer_findings += 1
        results.append({"name": "shutdown_leak_audit", "ok": False,
                        "error": str(exc)[:500]})
    except Exception as exc:
        # any OTHER teardown failure must still land in the report — the
        # CI artifact is most valuable exactly when chaos broke teardown
        results.append({"name": "cluster_shutdown", "ok": False,
                        "error": repr(exc)[:500]})
    unrecovered = sum(1 for r in results if not r.get("ok"))
    # crash dossiers (obs/recorder.py): every SIGKILL the scenarios injected
    # made the head write one — attach the inventory so a failed run's
    # artifact carries the victims' final spans/logs, not just verdicts
    dossier_dir = os.environ.get("RAYDP_TPU_DOSSIER_DIR", "")
    dossiers: List[str] = []
    if dossier_dir:
        from raydp_tpu.obs.recorder import list_dossiers

        dossiers = list_dossiers(dossier_dir)
    return {
        "sanitize_modes": os.environ.get("RAYDP_TPU_SANITIZE", ""),
        "scenarios": results,
        "unrecovered_queries": unrecovered,
        "sanitizer_findings": sanitizer_findings,
        "dossier_dir": dossier_dir or None,
        "dossiers": dossiers,
        "ok": unrecovered == 0 and sanitizer_findings == 0,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--quick", action="store_true",
                        help="CI slice: mid-shuffle + mid-fit lineage kills, "
                             "both block-service tiers, and the serving "
                             "replica kills (mid-request-stream and "
                             "mid-decode-stream)")
    parser.add_argument("--seed", type=int, default=None,
                        help="deterministic victim/timing selection "
                             "(unseeded keeps the fixed legacy choices)")
    parser.add_argument("--json", default="chaos_report.json",
                        help="report artifact path")
    args = parser.parse_args(argv)
    set_seed(args.seed)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault(
        "RAYDP_TPU_SANITIZE", "donation,lockdep,leaks-strict"
    )
    # crash dossiers land in one well-known dir (the heads the scenarios
    # boot inherit this env) so CI can upload them as artifacts on failure
    os.environ.setdefault(
        "RAYDP_TPU_DOSSIER_DIR", os.path.abspath("chaos_dossiers")
    )
    report = run(QUICK if args.quick else FULL)
    report["seed"] = args.seed
    with open(args.json, "w") as f:
        json.dump(report, f, indent=2, default=str)
    print(json.dumps({k: v for k, v in report.items() if k != "scenarios"}))
    if not report["ok"]:
        print("CHAOS FAIL", file=sys.stderr)
        return 1
    print("CHAOS OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
