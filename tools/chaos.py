"""Chaos-mode test harness: make executor failure boring.

SIGKILLs executors mid-shuffle, mid-compiled-dispatch, and mid-streaming-fit
(intentional kills — the head unregisters the victim's blocks, so the loss
is REAL, unlike a restartable crash whose shm survives) and asserts that

- every query/fit completes with a result byte-identical to an unkilled run
  (lineage recovery, docs/fault_tolerance.md),
- ``lineage.reexecuted_tasks`` stays within one map round per production
  level per kill, and
- the PR 4/5 runtime sanitizers (``RAYDP_TPU_SANITIZE=donation,lockdep,
  leaks-strict``) stay clean — the leak/lockdep auditors double as a
  recovery-correctness oracle. Gated: ZERO leaked shm segments / spill
  files at the strict shutdown audit, ZERO stranded threads per scenario;
  fd counts ride the report as advisory (the sanitize design's own stance
  on raw fd deltas).

Usage::

    RAYDP_TPU_SANITIZE=donation,lockdep,leaks-strict \
        python -m tools.chaos --quick --json chaos_report.json

``--quick`` runs the CI slice (one mid-shuffle kill + one mid-fit kill);
without it the full scenario list runs (adds the compiled-dispatch kill and
the elasticity round-trip). Exit code is non-zero when any query went
unrecovered or any sanitizer finding surfaced. The same scenario bodies are
reused by ``tests/test_chaos.py`` via the importable helpers below.
"""
# raydp-lint: disable-file=print-diagnostics (standalone CI tool: its stdout IS the report, there is no obs role to tag)

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Callable, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


# ---------------------------------------------------------------------------
# primitives (importable by tests/test_chaos.py)
# ---------------------------------------------------------------------------


def kill_executor(session, handle=None, index: int = 0):
    """SIGKILL one executor with NO restart — the real-loss chaos primitive:
    the head unregisters (tombstones) its blocks and unlinks their segments,
    so any surviving reference must come back through lineage recovery. The
    dead owner is recorded in the store so stale head-bypass locations
    fast-path to OwnerDiedError. Returns the victim handle."""
    from raydp_tpu.store import object_store as store

    victim = handle if handle is not None else session.executors[index]
    victim.kill(no_restart=True)
    store.note_owner_dead(victim._actor_id)
    return victim


def delayed_kill(session, delay_s: float, index: int = 0) -> threading.Thread:
    """Arm a timer thread that SIGKILLs an executor mid-whatever-is-running.
    Join it after the workload completes."""

    def _fire():
        time.sleep(delay_s)
        try:
            kill_executor(session, index=index)
        except Exception:  # raydp-lint: disable=swallowed-exceptions (chaos timer: the victim may already be gone, racing scenario teardown)
            pass

    thread = threading.Thread(target=_fire, name="chaos-killer", daemon=True)
    thread.start()
    return thread


def block_owner_executor(session, ds):
    """An executor handle that owns at least one of the dataset's blocks
    (killing it makes the loss real), or None."""
    from raydp_tpu.store import object_store as store

    owners = {store.owner_of(b) for b in ds.blocks}
    for handle in session.executors:
        if handle._actor_id in owners:
            return handle
    return None


def lineage_counters() -> dict:
    from raydp_tpu import obs

    return {
        "reexecuted_tasks": int(
            obs.metrics.counter("lineage.reexecuted_tasks").value
        ),
        "recovered_blocks": int(
            obs.metrics.counter("lineage.recovered_blocks").value
        ),
    }


def sanitizer_report() -> dict:
    """The current process's leak inventory (the cluster-level audit runs at
    shutdown; chaos scenarios also sample between kills)."""
    from raydp_tpu import sanitize

    if not sanitize.leaks_enabled():
        return {"enabled": False}
    report = sanitize.leak_report()
    return {
        "enabled": True,
        "shm": len(report["shm"]),
        "spill": len(report["spill"]),
        "fds": report["fds"],
        "threads": report["threads"],
    }


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def _fresh_session(name: str, executors: int = 2):
    import raydp_tpu

    return raydp_tpu.init_etl(
        name, num_executors=executors, executor_cores=1,
        executor_memory="300M",
    )


def scenario_mid_shuffle(rows: int = 120_000) -> dict:
    """Kill a block-holding executor between a shuffle's map and reduce
    rounds (deterministic window: the map outputs exist, the reduce hasn't
    read them) and while a full query is in flight (timed kill)."""
    import raydp_tpu
    from raydp_tpu.etl import functions as F
    from raydp_tpu.exchange import dataframe_to_dataset, dataset_to_dataframe

    session = _fresh_session("chaos-shuffle")
    try:
        # deterministic half: a shuffle whose SOURCE blocks are executor-
        # owned loses real data when the owner dies — the map round must
        # lineage-recover them mid-exchange
        src = session.range(rows, num_partitions=8).with_column(
            "k", F.col("id") % 13
        )
        ds = dataframe_to_dataset(src)
        df = dataset_to_dataframe(session, ds)
        clean = df.group_by("k").count().sort("k").collect()
        before = lineage_counters()
        victim = block_owner_executor(session, ds)
        kill_executor(session, handle=victim)
        time.sleep(0.3)
        chaos = df.group_by("k").count().sort("k").collect()
        session.request_total_executors(2)  # restore the pool

        # racing half: a timed kill lands wherever it lands (map dispatch,
        # between rounds, reduce read) — every window must hold
        killer = delayed_kill(session, 0.05, index=0)
        chaos2 = df.group_by("k").count().sort("k").collect()
        killer.join()
        session.request_total_executors(2)

        after = lineage_counters()
        reexecuted = after["reexecuted_tasks"] - before["reexecuted_tasks"]
        identical = chaos == clean and chaos2 == clean
        # bound: ≤ one map round (8 tasks) per production LEVEL per kill —
        # recovery transitively re-materializes the lost blocks' source
        # inputs too (one extra level here), and this scenario injects TWO
        # kills: 8 × 2 levels × 2 kills
        bound = 32
        return {
            "name": "mid_shuffle_kill",
            "ok": bool(identical and reexecuted >= 1),
            "byte_identical": bool(identical),
            "reexecuted_tasks": reexecuted,
            "reexecution_bound": bound,
            "within_bound": reexecuted <= bound,
        }
    finally:
        raydp_tpu.stop_etl()


def scenario_mid_compiled(rows: int = 50_000) -> dict:
    """Kill the owner of a materialized dataset's blocks, then run a
    COMPILED (plan-cache + run_plan) query over it: the compiled dispatch's
    lost-block fallback must lineage-recover and re-run."""
    import raydp_tpu
    from raydp_tpu.etl import functions as F
    from raydp_tpu.exchange import dataframe_to_dataset, dataset_to_dataframe

    session = _fresh_session("chaos-compiled")
    try:
        src = session.range(rows, num_partitions=4).with_column(
            "x", F.col("id") * 3
        )
        ds = dataframe_to_dataset(src)
        df = dataset_to_dataframe(session, ds)
        clean = df.filter(F.col("x") % 2 == 0).count()
        before = lineage_counters()

        victim = block_owner_executor(session, ds)
        assert victim is not None
        kill_executor(session, handle=victim)
        time.sleep(0.5)
        chaos = df.filter(F.col("x") % 2 == 0).count()
        session.request_total_executors(2)

        after = lineage_counters()
        reexecuted = after["reexecuted_tasks"] - before["reexecuted_tasks"]
        return {
            "name": "mid_compiled_dispatch_kill",
            "ok": chaos == clean and reexecuted >= 1,
            "byte_identical": chaos == clean,
            "reexecuted_tasks": reexecuted,
            "reexecution_bound": 4,
            "within_bound": reexecuted <= 4,
        }
    finally:
        raydp_tpu.stop_etl()


def scenario_mid_fit(rows: int = 2048) -> dict:
    """SIGKILL the executor owning the training blocks mid-streaming-fit:
    the streaming iterator's block reads lineage-recover and the fit's
    final params must be byte-identical to an unkilled run."""
    import numpy as np
    import pandas as pd

    import raydp_tpu
    from raydp_tpu.exchange import dataframe_to_dataset

    def _fit(session, ds, kill_after_steps: Optional[int]) -> dict:
        import jax

        from raydp_tpu.estimator import JaxEstimator
        from raydp_tpu.models import MLPRegressor

        est = JaxEstimator(
            model=MLPRegressor(),
            optimizer="adam",
            loss="mse",
            feature_columns=["a", "b"],
            label_column="y",
            batch_size=256,
            num_epochs=2,
            learning_rate=1e-3,
            shuffle=True,
            seed=0,
            streaming=True,
            donate_state=False,
        )
        if kill_after_steps is not None:
            # lose the blocks FOR REAL before the stream starts: the fit's
            # block reads then recover through lineage WHILE it runs (a
            # timed kill on data this small usually lands after the last
            # read and proves nothing)
            victim = block_owner_executor(session, ds)
            if victim is not None:
                kill_executor(session, handle=victim)
                time.sleep(0.3)
        est.fit(ds)
        params = est.get_model().params
        leaves = jax.tree_util.tree_leaves(params)
        return {
            "digest": [float(np.asarray(leaf).sum()) for leaf in leaves],
            "raw": [np.asarray(leaf).copy() for leaf in leaves],
        }

    session = _fresh_session("chaos-fit")
    try:
        rng = np.random.default_rng(3)
        pdf = pd.DataFrame(
            {
                "a": rng.random(rows).astype(np.float32),
                "b": rng.random(rows).astype(np.float32),
            }
        )
        pdf["y"] = 2 * pdf["a"] + 3 * pdf["b"]
        df = session.from_pandas(pdf, num_partitions=4)
        # materialize through the EXECUTORS so the blocks are executor-owned
        # (a from_pandas source is driver-owned — killing an executor would
        # lose nothing); repartition keeps the rows bit-identical
        ds = dataframe_to_dataset(df.repartition(4))
        clean = _fit(session, ds, kill_after_steps=None)
        before = lineage_counters()
        chaos = _fit(session, ds, kill_after_steps=1)
        session.request_total_executors(2)
        after = lineage_counters()
        identical = all(
            np.array_equal(c, k) for c, k in zip(clean["raw"], chaos["raw"])
        )
        reexecuted = after["reexecuted_tasks"] - before["reexecuted_tasks"]
        return {
            "name": "mid_streaming_fit_kill",
            "ok": bool(identical and reexecuted >= 1),
            "byte_identical": bool(identical),
            "reexecuted_tasks": reexecuted,
            "reexecution_bound": 8,
            "within_bound": reexecuted <= 8,
        }
    finally:
        raydp_tpu.stop_etl()


def scenario_elasticity() -> dict:
    """Scale-out under sustained queue depth (warm zygote fork — timed),
    then scale-in of a block-holding executor: no query may lose data."""
    import raydp_tpu
    from raydp_tpu.etl import functions as F
    from raydp_tpu.exchange import dataframe_to_dataset

    session = _fresh_session("chaos-elastic", executors=1)
    try:
        t0 = time.perf_counter()
        session.request_total_executors(2)
        scale_out_s = time.perf_counter() - t0
        # materialize AFTER the scale-out so blocks land on both executors;
        # kill_executors takes victims from the pool's tail — the new
        # executor — which then holds blocks (the scale-in-with-data case)
        df = session.range(20_000, num_partitions=4).with_column(
            "v", F.col("id") + 1
        )
        ds = dataframe_to_dataset(df)
        expected = ds.count()
        session.kill_executors(1, min_keep=1)
        survived = ds.to_arrow().num_rows == expected
        ok = survived and len(session.executors) >= 1
        return {
            "name": "elastic_round_trip",
            "ok": bool(ok),
            "scale_out_s": round(scale_out_s, 3),
            "scale_out_warm": scale_out_s < 1.0,
            "data_survived_scale_in": bool(survived),
        }
    finally:
        raydp_tpu.stop_etl()


QUICK = (scenario_mid_shuffle, scenario_mid_fit)
FULL = (
    scenario_mid_shuffle,
    scenario_mid_compiled,
    scenario_mid_fit,
    scenario_elasticity,
)


def run(scenarios) -> dict:
    from raydp_tpu import sanitize
    from raydp_tpu.cluster import api as cluster_api

    results: List[dict] = []
    for scenario in scenarios:
        name = scenario.__name__
        t0 = time.perf_counter()
        try:
            entry = scenario()
        except Exception as exc:  # one scenario must not hide the rest
            entry = {"name": name, "ok": False, "error": repr(exc)[:500]}
        entry["seconds"] = round(time.perf_counter() - t0, 2)
        # leak inventory AFTER the scenario's session stopped. GATED here:
        # stranded THREADS (stable zero — a recovery that leaks a producer
        # or reaper thread shows up immediately). Reported only: fds (the
        # sanitize design treats raw fd counts as advisory — library
        # internals open them unpredictably) and shm/spill (driver-owned
        # blocks legitimately live until cluster shutdown, where the
        # leaks-strict audit below is exact and fatal).
        entry["sanitizer"] = sanitizer_report()
        if entry["sanitizer"].get("threads"):
            entry["ok"] = False
            entry["sanitizer_fail"] = (
                f"{entry['sanitizer']['threads']} stranded thread(s)"
            )
        results.append(entry)
        print(f"[chaos] {entry.get('name', name)}: "
              f"{'OK' if entry.get('ok') else 'FAILED'} "
              f"({entry['seconds']}s)")
    # final teardown audit: leaks-strict raises on any leaked segment —
    # the recovery-correctness oracle the harness exists to arm
    sanitizer_findings = 0
    try:
        cluster_api.shutdown()
    except sanitize.LeakError as exc:
        sanitizer_findings += 1
        results.append({"name": "shutdown_leak_audit", "ok": False,
                        "error": str(exc)[:500]})
    except Exception as exc:
        # any OTHER teardown failure must still land in the report — the
        # CI artifact is most valuable exactly when chaos broke teardown
        results.append({"name": "cluster_shutdown", "ok": False,
                        "error": repr(exc)[:500]})
    unrecovered = sum(1 for r in results if not r.get("ok"))
    return {
        "sanitize_modes": os.environ.get("RAYDP_TPU_SANITIZE", ""),
        "scenarios": results,
        "unrecovered_queries": unrecovered,
        "sanitizer_findings": sanitizer_findings,
        "ok": unrecovered == 0 and sanitizer_findings == 0,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--quick", action="store_true",
                        help="CI slice: one mid-shuffle + one mid-fit kill")
    parser.add_argument("--json", default="chaos_report.json",
                        help="report artifact path")
    args = parser.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault(
        "RAYDP_TPU_SANITIZE", "donation,lockdep,leaks-strict"
    )
    report = run(QUICK if args.quick else FULL)
    with open(args.json, "w") as f:
        json.dump(report, f, indent=2, default=str)
    print(json.dumps({k: v for k, v in report.items() if k != "scenarios"}))
    if not report["ok"]:
        print("CHAOS FAIL", file=sys.stderr)
        return 1
    print("CHAOS OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
