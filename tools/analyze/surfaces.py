"""Whole-surface extraction: the shared registry the closure rules consume.

One walk over the project collects every *string-keyed surface* the cluster
is steered by — metric instrumentation and read sites, conf-key reads with
their defaults, ``RAYDP_TPU_*`` env reads — plus every name the docs claim
exists (markdown table rows in ``docs/*.md``). The registry rules
(metric-registry / conf-registry / env-registry) then check the two-way
closure: a name written in one place and read in another is a contract, and
a typo'd metric is a controller silently steering on nothing
["Bugs as Deviant Behavior", Engler et al. 2001].

Dynamic names are kept as *patterns*: an f-string hole becomes a ``<*>``
segment wildcard (``f"tenant.{ns}.bytes_stored"`` -> ``tenant.<*>.bytes_stored``),
matching the docs' own placeholder convention (``tenant.<ns>.bytes_stored``).
The time-series layer's fan-out suffixes (``.max``/``.p50``/``.p99``/
``.delta``/``.count``/``.sum``/``.mean``/``.min``) are stripped before
read->write matching so a scrape-side read of ``serve.ttft_ms.p99`` resolves
to the ``serve.ttft_ms`` histogram.

Everything here is stdlib-only (ast + re) so the analyzer keeps running
before dependency install in CI.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# ---------------------------------------------------------------------------
# name shapes and matching
# ---------------------------------------------------------------------------

# dotted metric name (holes already normalized to <*>)
_METRIC_SHAPE = re.compile(r"^[a-z][a-z0-9_]*(\.([a-z0-9_]+|<\*>))+$")
# conf keys allow camelCase segments (etl.dynamicAllocation.maxMemPressure)
_CONF_SHAPE = re.compile(r"^[a-z][A-Za-z0-9_]*(\.[A-Za-z0-9_]+)+$")
_ENV_SHAPE = re.compile(r"^RAYDP_TPU_[A-Z0-9_]+$")

# suffixes the time-series layer fans out of one instrument — a read of
# <name>.<suffix> is a read of <name>
FANOUT_SUFFIXES = ("max", "min", "p50", "p99", "count", "sum", "mean", "delta")

_WILD = "<*>"


def pattern_regex(pattern: str) -> "re.Pattern":
    """Compile a name pattern (``<*>`` = exactly one dotted segment) to a
    regex. Docs placeholders (``<ns>``, ``<role>``, ``<method>``, ...) are
    normalized to ``<*>`` before this is called."""
    parts = [
        r"[^.]+" if seg == _WILD else re.escape(seg)
        for seg in pattern.split(".")
    ]
    return re.compile(r"\.".join(parts) + r"\Z")


def _probe(pattern: str) -> str:
    """A concrete example name for ``pattern`` (holes become one segment)."""
    return pattern.replace(_WILD, "xWILDx")


def patterns_match(a: str, b: str) -> bool:
    """True when the two name patterns can describe the same metric: either
    regex covers the other's example form (wildcards unify)."""
    if a == b:
        return True
    return bool(
        pattern_regex(a).match(_probe(b)) or pattern_regex(b).match(_probe(a))
    )


def strip_fanout(name: str) -> str:
    head, _, tail = name.rpartition(".")
    if head and tail in FANOUT_SUFFIXES:
        return head
    return name


# ---------------------------------------------------------------------------
# record types
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class MetricUse:
    pattern: str           # name pattern, holes as <*>
    mode: str              # "write" | "read" | "mention"
    kind: str              # counter/gauge/histogram/query/get/subscript/wrapper
    path: str
    line: int


@dataclasses.dataclass
class ConfRead:
    key: str
    has_default: bool
    path: str
    line: int


@dataclasses.dataclass
class EnvUse:
    name: str
    mode: str              # "read" | "set"
    path: str
    line: int


@dataclasses.dataclass
class DocEntry:
    name: str              # pattern (placeholders normalized to <*>)
    kind: str              # "metric" | "conf" | "env"
    path: str
    line: int


class DocFile:
    """One markdown file: text, table rows, and raydp-lint suppressions
    (HTML-comment form: ``<!-- raydp-lint: disable=metric-registry -->``)."""

    def __init__(self, path: str, display_path: str, text: str):
        self.path = path
        self.display_path = display_path
        self.lines = text.splitlines()
        self._line_suppressions: Dict[int, Set[str]] = {}
        self._file_suppressions: Set[str] = set()
        marker = re.compile(
            r"raydp-lint:\s*disable(?P<scope>-file)?=(?P<rules>[A-Za-z0-9_,\- ]+)"
        )
        for i, line in enumerate(self.lines):
            m = marker.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
            if m.group("scope"):
                self._file_suppressions |= rules
            else:
                self._line_suppressions.setdefault(i + 1, set()).update(rules)

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self._file_suppressions or "all" in self._file_suppressions:
            return True
        rules = self._line_suppressions.get(line, ())
        return rule in rules or "all" in rules


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------


class Surfaces:
    def __init__(self):
        self.metric_writes: List[MetricUse] = []
        self.metric_reads: List[MetricUse] = []      # strong reads
        self.metric_mentions: List[MetricUse] = []   # dict-get / wrapper reads
        self.dynamic_metric_sites: List[Tuple[str, int, str]] = []
        self.conf_reads: List[ConfRead] = []
        self.env_reads: List[EnvUse] = []
        self.env_sets: List[EnvUse] = []
        self.env_consts: Dict[str, str] = {}         # CONST name -> var value
        self.doc_metrics: List[DocEntry] = []
        self.doc_confs: List[DocEntry] = []
        self.doc_envs: List[DocEntry] = []
        self.doc_files: Dict[str, DocFile] = {}
        # full-surface mode: the project under analysis includes both the
        # package and the bench/tools readers, so doc-side (dead-row) and
        # whole-program checks are meaningful. Partial sweeps (one
        # subdirectory) only get code-side checks.
        self.full_surface: bool = False

    # -- derived views ----------------------------------------------------

    def write_patterns(self) -> List[str]:
        seen, out = set(), []
        for w in self.metric_writes:
            if w.pattern not in seen:
                seen.add(w.pattern)
                out.append(w.pattern)
        return out

    def write_families(self) -> Set[str]:
        return {w.pattern.split(".", 1)[0] for w in self.metric_writes}

    def conf_keys(self) -> Set[str]:
        return {c.key for c in self.conf_reads}

    def doc_conf_keys(self) -> Set[str]:
        return {d.name for d in self.doc_confs}

    def has_writer(self, read_pattern: str) -> bool:
        name = read_pattern
        for candidate in (name, strip_fanout(name)):
            for w in self.metric_writes:
                if patterns_match(candidate, w.pattern):
                    return True
        return False

    def is_documented_metric(self, write_pattern: str) -> bool:
        return any(
            patterns_match(write_pattern, d.name) for d in self.doc_metrics
        )


# ---------------------------------------------------------------------------
# python-side extraction
# ---------------------------------------------------------------------------

_METRIC_FACTORIES = {"counter", "gauge", "histogram"}
_METRIC_WRITE_ATTRS = {"inc", "set", "observe", "set_watermark"}
_METRIC_READ_ATTRS = {"value", "quantile", "snapshot"}
_QUERY_FUNCS = {"query_metrics", "windowed_local", "windowed"}
_CONF_RECEIVERS = {"configs", "conf", "cfg", "merged"}
# receivers whose .get("a.b") is definitely NOT a metric lookup
_NON_METRIC_RECEIVERS = _CONF_RECEIVERS | {
    "environ", "kwargs", "opts", "labels", "args", "os",
}


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _name_patterns(node: ast.AST) -> List[str]:
    """Resolve a metric-name expression to name patterns. Literal -> itself;
    f-string -> holes as <*> (a hole mid-segment widens to the segment);
    conditional -> both arms. [] = dynamic/unresolvable."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, ast.JoinedStr):
        buf = []
        for part in node.values:
            if isinstance(part, ast.Constant) and isinstance(part.value, str):
                buf.append(part.value)
            else:
                buf.append(_WILD)
        raw = "".join(buf)
        # a hole glued to text inside one segment (e.g. "lineage_{k}")
        # widens that whole segment to <*>
        segs = [
            _WILD if _WILD in seg else seg for seg in raw.split(".")
        ]
        return [".".join(segs)]
    if isinstance(node, ast.IfExp):
        return _name_patterns(node.body) + _name_patterns(node.orelse)
    return []


def _parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _call_default(call: ast.Call) -> bool:
    """Does this ``.get(key, ...)``-shaped call pass an explicit default?"""
    if len(call.args) >= 2:
        return True
    return any(kw.arg == "default" for kw in call.keywords)


@dataclasses.dataclass
class _ConfWrapper:
    prefix: str
    param: str
    param_has_default: bool


def _conf_wrapper_of(fn: ast.AST) -> Optional[_ConfWrapper]:
    """Detect a local conf-read wrapper: a function whose body calls
    ``<conf-ish>.get(param)`` or ``<conf-ish>.get(f"prefix{param}")``.
    Covers the session's ``_flag(name, default)`` helper and
    serve/config.py's ``get(key, default)`` (prefix ``serve.``)."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    params = fn.args.args
    if not params:
        return None
    first = params[0].arg
    if first in ("self", "cls"):
        if len(params) < 2:
            return None
        first = params[1].arg
    n_defaults = len(fn.args.defaults)
    # does the param after the key param (conventionally "default") or the
    # key param's own position carry a default? we only need to know whether
    # a call relying on wrapper defaults still "declares" one: any default
    # on the wrapper's second parameter counts
    has_default_param = n_defaults >= 1
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if not isinstance(node.func, ast.Attribute) or node.func.attr != "get":
            continue
        recv = _dotted(node.func.value) or ""
        if recv.rsplit(".", 1)[-1] not in _CONF_RECEIVERS:
            continue
        if not node.args:
            continue
        key = node.args[0]
        if isinstance(key, ast.Name) and key.id == first:
            return _ConfWrapper("", first, has_default_param)
        if isinstance(key, ast.JoinedStr) and len(key.values) == 2:
            pre, hole = key.values
            if (
                isinstance(pre, ast.Constant)
                and isinstance(pre.value, str)
                and isinstance(hole, ast.FormattedValue)
                and isinstance(hole.value, ast.Name)
                and hole.value.id == first
            ):
                return _ConfWrapper(pre.value, first, has_default_param)
    return None


def _get_wrapper_of(fn: ast.AST) -> bool:
    """Detect a generic lookup wrapper: single-key function whose body
    subscripts/``.get``s an arbitrary mapping with its first param (bench's
    ``total(name)`` over dump_metrics snapshots). Calls with literal args
    become metric *mentions*."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    params = [a.arg for a in fn.args.args if a.arg not in ("self", "cls")]
    if not params:
        return False
    first = params[0]
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and node.args
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id == first
            ):
                return True
        elif isinstance(node, ast.Subscript):
            sl = node.slice
            if isinstance(sl, ast.Name) and sl.id == first:
                return True
    return False


def _extract_python(src, surfaces: Surfaces) -> None:
    tree = src.tree
    if tree is None:
        return
    parents = _parent_map(tree)
    path, add = src.display_path, None

    # module-level env-name constants: NAME = "RAYDP_TPU_X"
    for node in tree.body if hasattr(tree, "body") else []:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
            and _ENV_SHAPE.match(node.value.value)
        ):
            surfaces.env_consts[node.targets[0].id] = node.value.value

    # wrapper discovery (per file)
    conf_wrappers: Dict[str, _ConfWrapper] = {}
    get_wrappers: Set[str] = set()
    # registry aliases: `m = obs.metrics` makes `m.counter(...)` a write
    metric_aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cw = _conf_wrapper_of(node)
            if cw is not None:
                conf_wrappers[node.name] = cw
            elif _get_wrapper_of(node):
                get_wrappers.add(node.name)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            value = _dotted(node.value) or ""
            if (
                isinstance(target, ast.Name)
                and value.rsplit(".", 1)[-1] == "metrics"
            ):
                metric_aliases.add(target.id)

    def resolve_env_arg(arg: ast.AST) -> Optional[str]:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value if _ENV_SHAPE.match(arg.value) else None
        if isinstance(arg, ast.Name):
            return surfaces.env_consts.get(arg.id)
        if isinstance(arg, ast.Attribute):  # common.SESSION_ENV style
            return surfaces.env_consts.get(arg.attr)
        return None

    for node in ast.walk(tree):
        # ---- metric factory calls: <...metrics>.counter|gauge|histogram(n)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            recv = _dotted(node.func.value) or ""
            recv_last = recv.rsplit(".", 1)[-1]
            if (
                attr in _METRIC_FACTORIES
                and ("metrics" in recv_last or recv_last in metric_aliases)
                and node.args
            ):
                pats = _name_patterns(node.args[0])
                parent = parents.get(node)
                mode = "write"
                if isinstance(parent, ast.Attribute):
                    if parent.attr in _METRIC_READ_ATTRS:
                        mode = "read"
                    elif parent.attr in _METRIC_WRITE_ATTRS:
                        mode = "write"
                if not pats:
                    surfaces.dynamic_metric_sites.append(
                        (path, node.lineno, mode)
                    )
                for p in pats:
                    use = MetricUse(p, mode, attr, path, node.lineno)
                    (surfaces.metric_writes if mode == "write"
                     else surfaces.metric_reads).append(use)

            # ---- windowed/query reads
            elif attr in _QUERY_FUNCS and node.args:
                for p in _name_patterns(node.args[0]):
                    surfaces.metric_reads.append(
                        MetricUse(p, "read", "query", path, node.lineno)
                    )

            # ---- dict-style lookups: X.get("a.b.c", ...)
            elif attr == "get" and node.args:
                key = node.args[0]
                lit = (
                    key.value
                    if isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                    else None
                )
                if recv_last in ("environ", "os.environ") or recv.endswith(
                    "os.environ"
                ):
                    env = resolve_env_arg(key)
                    if env:
                        surfaces.env_reads.append(
                            EnvUse(env, "read", path, node.lineno)
                        )
                elif lit is not None and "." in lit:
                    if recv_last in _CONF_RECEIVERS:
                        if _CONF_SHAPE.match(lit):
                            surfaces.conf_reads.append(
                                ConfRead(
                                    lit, _call_default(node), path, node.lineno
                                )
                            )
                    elif (
                        recv_last not in _NON_METRIC_RECEIVERS
                        and _METRIC_SHAPE.match(lit)
                    ):
                        surfaces.metric_mentions.append(
                            MetricUse(lit, "mention", "get", path, node.lineno)
                        )

        # ---- plain-call wrappers: _flag("planner.x"), get("max_retries"),
        #      total("rpc.bytes_over_wire")
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            fname, lit = node.func.id, node.args[0].value
            if fname in conf_wrappers:
                cw = conf_wrappers[fname]
                key = cw.prefix + lit
                if _CONF_SHAPE.match(key):
                    surfaces.conf_reads.append(
                        ConfRead(
                            key,
                            _call_default(node) or cw.param_has_default,
                            path,
                            node.lineno,
                        )
                    )
            elif fname in get_wrappers and _METRIC_SHAPE.match(lit):
                if "." in lit:
                    surfaces.metric_mentions.append(
                        MetricUse(lit, "mention", "wrapper", path, node.lineno)
                    )

        # ---- os.getenv(...)
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, (ast.Name, ast.Attribute))
        ):
            fdot = _dotted(node.func) or ""
            if fdot.rsplit(".", 1)[-1] == "getenv" and node.args:
                env = resolve_env_arg(node.args[0])
                if env:
                    surfaces.env_reads.append(
                        EnvUse(env, "read", path, node.lineno)
                    )

        # ---- environ["X"] loads/stores, env-dict stores, setdefault/pop
        if isinstance(node, ast.Subscript):
            # synthesized metrics: snapshot["trace.spans_dropped"] = {...}
            # (the head injects per-process series into a scrape snapshot)
            if isinstance(node.ctx, ast.Store):
                key_lit = (
                    node.slice.value
                    if isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                    else None
                )
                recv = _dotted(node.value) or ""
                recv_last = recv.rsplit(".", 1)[-1]
                if (
                    key_lit
                    and _METRIC_SHAPE.match(key_lit)
                    and ("metric" in recv_last or "snapshot" in recv_last)
                ):
                    surfaces.metric_writes.append(
                        MetricUse(key_lit, "write", "dict", path, node.lineno)
                    )
            env = resolve_env_arg(node.slice)
            if env:
                recv = _dotted(node.value) or ""
                is_environ = recv.endswith("environ")
                if isinstance(node.ctx, ast.Store):
                    surfaces.env_sets.append(
                        EnvUse(env, "set", path, node.lineno)
                    )
                elif is_environ:
                    surfaces.env_reads.append(
                        EnvUse(env, "read", path, node.lineno)
                    )
                else:
                    # a literal RAYDP_TPU_* subscript on an arbitrary dict
                    # (child-process env assembly) still references the var
                    surfaces.env_sets.append(
                        EnvUse(env, "set", path, node.lineno)
                    )
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in ("setdefault", "pop") and node.args:
                recv = _dotted(node.func.value) or ""
                if recv.endswith("environ"):
                    env = resolve_env_arg(node.args[0])
                    if env:
                        surfaces.env_reads.append(
                            EnvUse(env, "read", path, node.lineno)
                        )
        # ---- "RAYDP_TPU_X" in os.environ
        if isinstance(node, ast.Compare) and node.ops:
            if isinstance(node.ops[0], (ast.In, ast.NotIn)):
                recv = _dotted(node.comparators[0]) or ""
                if recv.endswith("environ"):
                    env = resolve_env_arg(node.left)
                    if env:
                        surfaces.env_reads.append(
                            EnvUse(env, "read", path, node.lineno)
                        )
        # ---- dict-literal env keys (spawner env dicts)
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if (
                    isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and _ENV_SHAPE.match(k.value)
                ):
                    surfaces.env_sets.append(
                        EnvUse(k.value, "set", path, node.lineno)
                    )


# ---------------------------------------------------------------------------
# docs-side extraction
# ---------------------------------------------------------------------------

_METRIC_HEADERS = {"metric", "series"}
_CONF_HEADERS = {"knob", "key", "conf", "conf key", "option", "setting", "env",
                 "variable", "env var"}
_PLACEHOLDER = re.compile(r"<[A-Za-z_][A-Za-z0-9_]*>")
_BACKTICK = re.compile(r"`([^`]+)`")
_ENV_NAME = re.compile(r"RAYDP_TPU_[A-Z0-9_]+")


def _cells(line: str) -> List[str]:
    if not line.strip().startswith("|"):
        return []
    return [c.strip() for c in line.strip().strip("|").split("|")]


def _expand_braces(token: str) -> List[str]:
    m = re.search(r"\{([^{}]+)\}", token)
    if not m:
        return [token]
    head, tail = token[: m.start()], token[m.end():]
    out: List[str] = []
    for alt in m.group(1).split(","):
        out.extend(_expand_braces(head + alt.strip() + tail))
    return out


def _doc_cell_names(cell: str, shape: "re.Pattern") -> List[str]:
    """Name patterns from a table row's first cell. Handles brace fan-out,
    ``<ns>``-style placeholders, ``(+`.max`)`` annotations, and leading-dot
    shorthand (``.veto.slots`` / ``.max_replicas`` continues the previous
    name by replacing its last k segments)."""
    names: List[str] = []
    for token in _BACKTICK.findall(cell):
        token = token.strip()
        for t in _expand_braces(token):
            t = _PLACEHOLDER.sub(_WILD, t)
            if t.startswith("."):
                segs = [s for s in t[1:].split(".") if s]
                if segs and all(s in FANOUT_SUFFIXES for s in segs):
                    continue  # fan-out annotation, not a name
                if not names or not segs:
                    continue
                base = names[-1].split(".")
                if len(base) > len(segs):
                    names.append(".".join(base[: -len(segs)] + segs))
                continue
            if shape.match(t):
                names.append(t)
    return names


def _extract_doc(doc: DocFile, surfaces: Surfaces) -> None:
    lines = doc.lines
    table_kind: Optional[str] = None
    expect_sep = False
    for i, line in enumerate(lines):
        lineno = i + 1
        cells = _cells(line)
        if not cells:
            table_kind = None
            expect_sep = False
        elif expect_sep:
            expect_sep = False
            if not set("".join(cells)) <= set("-: "):
                table_kind = None
        elif table_kind is None:
            header = cells[0].lower().strip("`*")
            if header in _METRIC_HEADERS:
                table_kind = "metric"
                expect_sep = True
            elif header in _CONF_HEADERS:
                table_kind = "conf"
                expect_sep = True
        else:
            first = cells[0]
            if table_kind == "metric":
                for name in _doc_cell_names(first, _METRIC_SHAPE):
                    surfaces.doc_metrics.append(
                        DocEntry(name, "metric", doc.display_path, lineno)
                    )
            else:
                for token in _BACKTICK.findall(first):
                    token = token.strip()
                    if _ENV_SHAPE.match(token):
                        surfaces.doc_envs.append(
                            DocEntry(token, "env", doc.display_path, lineno)
                        )
                for name in _doc_cell_names(first, _CONF_SHAPE):
                    if not _ENV_SHAPE.match(name):
                        surfaces.doc_confs.append(
                            DocEntry(name, "conf", doc.display_path, lineno)
                        )
        # env vars are "documented" by ANY backticked mention in the docs —
        # tables are preferred but an inline mention (`RAYDP_TPU_X=1` or
        # an expression containing the name) is still a contract
        for span in _BACKTICK.findall(line):
            for env in _ENV_NAME.findall(span):
                surfaces.doc_envs.append(
                    DocEntry(env, "env", doc.display_path, lineno)
                )


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

# the files whose presence in the project means "the whole surface is in
# scope": the metric registry itself plus the bench harness (the scrape /
# ledger reader side). Doc-side dead-row checks and whole-program
# read-without-writer checks only run then — a partial sweep of one
# subdirectory must not flag every doc row as dead.
_FULL_SURFACE_MARKERS = ("raydp_tpu/obs/metrics.py", "bench.py")

DOC_GLOBS = ("docs",)


def extract(project, root: Optional[str] = None) -> Surfaces:
    surfaces = Surfaces()
    root = root or getattr(project, "root", None) or os.getcwd()

    present = {f.display_path.replace(os.sep, "/") for f in project}
    surfaces.full_surface = all(m in present for m in _FULL_SURFACE_MARKERS)

    for src in project:
        _extract_python(src, surfaces)
    # second pass: env-const resolution is global (SESSION_ENV defined in
    # cluster/common.py, read via `from ... import SESSION_ENV` elsewhere) —
    # re-run the env extraction once all consts are known
    if surfaces.env_consts:
        surfaces.env_reads.clear()
        surfaces.env_sets.clear()
        for src in project:
            _extract_env_only(src, surfaces)

    docs_dir = os.path.join(root, "docs")
    doc_paths: List[str] = []
    if os.path.isdir(docs_dir):
        doc_paths = [
            os.path.join(docs_dir, n)
            for n in sorted(os.listdir(docs_dir))
            if n.endswith(".md")
        ]
    readme = os.path.join(root, "README.md")
    if os.path.isfile(readme):
        doc_paths.append(readme)
    for p in doc_paths:
        try:
            with open(p, encoding="utf-8", errors="replace") as f:
                text = f.read()
        except OSError:  # raydp-lint: disable=swallowed-exceptions (doc vanished mid-scan; registry checks simply see fewer rows)
            continue
        display = os.path.relpath(p, root)
        doc = DocFile(p, display, text)
        surfaces.doc_files[display] = doc
        _extract_doc(doc, surfaces)
    return surfaces


def _extract_env_only(src, surfaces: Surfaces) -> None:
    """Env extraction with the complete cross-module const map (subset of
    :func:`_extract_python`; metric/conf surfaces are not touched)."""
    tree = src.tree
    if tree is None:
        return
    path = src.display_path

    def resolve(arg: ast.AST) -> Optional[str]:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value if _ENV_SHAPE.match(arg.value) else None
        if isinstance(arg, ast.Name):
            return surfaces.env_consts.get(arg.id)
        if isinstance(arg, ast.Attribute):
            return surfaces.env_consts.get(arg.attr)
        return None

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fdot = _dotted(node.func) or ""
            last = fdot.rsplit(".", 1)[-1]
            if last == "getenv" and node.args:
                env = resolve(node.args[0])
                if env:
                    surfaces.env_reads.append(
                        EnvUse(env, "read", path, node.lineno)
                    )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in ("get", "setdefault", "pop")
                and node.args
            ):
                recv = _dotted(node.func.value) or ""
                if recv.endswith("environ"):
                    env = resolve(node.args[0])
                    if env:
                        surfaces.env_reads.append(
                            EnvUse(env, "read", path, node.lineno)
                        )
        elif isinstance(node, ast.Subscript):
            env = resolve(node.slice)
            if env:
                if isinstance(node.ctx, ast.Store):
                    surfaces.env_sets.append(
                        EnvUse(env, "set", path, node.lineno)
                    )
                else:
                    recv = _dotted(node.value) or ""
                    mode = "read" if recv.endswith("environ") else "set"
                    (surfaces.env_reads if mode == "read"
                     else surfaces.env_sets).append(
                        EnvUse(env, mode, path, node.lineno)
                    )
        elif isinstance(node, ast.Compare) and node.ops:
            if isinstance(node.ops[0], (ast.In, ast.NotIn)):
                recv = _dotted(node.comparators[0]) or ""
                if recv.endswith("environ"):
                    env = resolve(node.left)
                    if env:
                        surfaces.env_reads.append(
                            EnvUse(env, "read", path, node.lineno)
                        )
        elif isinstance(node, ast.Dict):
            for k in node.keys:
                if (
                    isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and _ENV_SHAPE.match(k.value)
                ):
                    surfaces.env_sets.append(
                        EnvUse(k.value, "set", path, node.lineno)
                    )
