"""Analysis framework: file walking, parsing, suppressions, rule running.

A rule sees the whole :class:`Project` (parsed ASTs for every file in scope)
so cross-file protocol checks (rpc-protocol) and single-file pattern checks
share one walker and one suppression mechanism.

Suppression syntax (matched via the token stream, never inside strings):

- trailing comment — suppresses the named rules on that line::

      sock.close()  # raydp-lint: disable=swallowed-exceptions

- standalone comment line — suppresses on the next code line::

      # raydp-lint: disable=guarded-by  (monitor thread holds the lock)
      self.actors.pop(actor_id)

- file-wide — anywhere in the file::

      # raydp-lint: disable-file=print-diagnostics

``disable=all`` suppresses every rule. Suppressed findings still count in the
JSON report (``"suppressed": true``) so a suppression sweep stays auditable.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import sys
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_SUPPRESS_RE = re.compile(
    r"raydp-lint:\s*disable(?P<scope>-file)?=(?P<rules>[A-Za-z0-9_,\- ]+)"
)


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}{tag}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class SourceFile:
    """One parsed file: source text, AST, and its suppression map."""

    def __init__(self, path: str, display_path: str, text: str):
        self.path = path
        self.display_path = display_path
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(text, filename=path)
        except SyntaxError as exc:
            self.parse_error = f"{exc.msg} (line {exc.lineno})"
        self._line_suppressions: Dict[int, Set[str]] = {}
        self._file_suppressions: Set[str] = set()
        self._collect_suppressions()

    def _collect_suppressions(self) -> None:
        # standalone suppression comments apply to the next code line; track
        # them until a non-comment logical line consumes them
        pending: Set[str] = set()
        pending_lines: List[int] = []
        comment_only_lines: Set[int] = set()
        comments: List[Tuple[int, str]] = []
        try:
            for tok in tokenize.generate_tokens(io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    comments.append((tok.start[0], tok.string))
                    before = self.lines[tok.start[0] - 1][: tok.start[1]]
                    if not before.strip():
                        comment_only_lines.add(tok.start[0])
        except (tokenize.TokenError, SyntaxError):
            # fall back to a line regex; strings containing the marker would
            # be miscounted, but an untokenizable file rarely has any
            comments = [
                (i + 1, line) for i, line in enumerate(self.lines) if "#" in line
            ]
            comment_only_lines = {
                i + 1 for i, line in enumerate(self.lines)
                if line.strip().startswith("#")
            }
        rules_by_line: Dict[int, Set[str]] = {}
        for lineno, comment in comments:
            m = _SUPPRESS_RE.search(comment)
            if not m:
                continue
            rules = {
                r.strip() for r in m.group("rules").split(",") if r.strip()
            }
            if m.group("scope"):
                self._file_suppressions |= rules
            else:
                rules_by_line.setdefault(lineno, set()).update(rules)
        for lineno in sorted(rules_by_line):
            if lineno in comment_only_lines:
                pending |= rules_by_line[lineno]
                pending_lines.append(lineno)
            else:
                self._line_suppressions.setdefault(lineno, set()).update(
                    rules_by_line[lineno]
                )
        # attach each standalone run to the first following code line
        if pending:
            for lineno in pending_lines:
                target = lineno + 1
                while target <= len(self.lines) and (
                    target in comment_only_lines
                    or not self.lines[target - 1].strip()
                ):
                    target += 1
                self._line_suppressions.setdefault(target, set()).update(
                    rules_by_line[lineno]
                )

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self._file_suppressions or "all" in self._file_suppressions:
            return True
        rules = self._line_suppressions.get(line, ())
        return rule in rules or "all" in rules

    def finding(self, rule: str, node_or_line, message: str) -> Finding:
        if isinstance(node_or_line, int):
            line, col = node_or_line, 0
        else:
            line = getattr(node_or_line, "lineno", 1)
            col = getattr(node_or_line, "col_offset", 0)
        return Finding(
            rule=rule,
            path=self.display_path,
            line=line,
            col=col,
            message=message,
            suppressed=self.is_suppressed(rule, line),
        )


class Project:
    def __init__(self, files: Sequence[SourceFile], root: Optional[str] = None):
        self.files = list(files)
        self.root = root or os.getcwd()
        self._by_path = {f.display_path: f for f in self.files}
        self._surfaces = None
        self._rpc_surface = None

    def file(self, display_path: str) -> Optional[SourceFile]:
        return self._by_path.get(display_path)

    def surfaces(self):
        """Memoized whole-surface registry (see :mod:`tools.analyze.surfaces`):
        metric/conf/env read+write sites plus doc table rows. Shared by the
        registry-closure rules so the project is walked once, not per rule."""
        if self._surfaces is None:
            from tools.analyze import surfaces as _surf

            self._surfaces = _surf.extract(self, self.root)
        return self._surfaces

    def rpc_surface(self):
        """Memoized RPC wire surface (see :mod:`tools.analyze.rpc`): every
        handler and call site on the frame/actor/doorbell planes. Shared by
        the four rpc-* rules and the contract gate — one walk, not four."""
        if self._rpc_surface is None:
            from tools.analyze import rpc as _rpc

            self._rpc_surface = _rpc.extract(self)
        return self._rpc_surface

    def __iter__(self):
        return iter(self.files)


_SKIP_DIRS = {"__pycache__", ".git", "node_modules", ".venv", "build", "dist"}


def _excluded(display: str, exclude: Sequence[str]) -> bool:
    """fnmatch-style exclusion against the display (repo-relative) path; a
    bare directory pattern excludes everything under it."""
    import fnmatch

    display = display.replace(os.sep, "/")
    for pattern in exclude:
        pattern = pattern.rstrip("/").replace(os.sep, "/")
        if fnmatch.fnmatch(display, pattern) or fnmatch.fnmatch(
            display, pattern + "/*"
        ):
            return True
    return False


def iter_python_files(paths: Iterable[str]) -> List[str]:
    return [path for path, _ in _iter_python_files_with_origin(paths)]


def _iter_python_files_with_origin(
    paths: Iterable[str],
) -> List[Tuple[str, bool]]:
    """(path, explicit) pairs: explicitly-named files are marked so exclusion
    patterns (which exist to keep fixture dirs out of directory sweeps) never
    veto a file the caller asked for by name."""
    out: List[Tuple[str, bool]] = []
    for path in paths:
        if os.path.isfile(path):
            out.append((path, True))
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for name in sorted(filenames):
                if name.endswith(".py"):
                    out.append((os.path.join(dirpath, name), False))
    return out


def load_project(
    paths: Iterable[str],
    root: Optional[str] = None,
    exclude: Sequence[str] = (),
) -> Project:
    root = root or os.getcwd()
    files = []
    for path, explicit in _iter_python_files_with_origin(paths):
        display = os.path.relpath(path, root)
        if display.startswith(".."):
            display = path
        if exclude and not explicit and _excluded(display, exclude):
            continue
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                text = f.read()
        except OSError as exc:
            sys.stderr.write(f"raydp-lint: cannot read {path}: {exc}\n")
            continue
        files.append(SourceFile(path, display, text))
    return Project(files, root=root)


def run_rules(project: Project, rules) -> List[Finding]:
    findings: List[Finding] = []
    for src in project:
        if src.parse_error is not None:
            findings.append(
                Finding(
                    rule="parse-error",
                    path=src.display_path,
                    line=1,
                    col=0,
                    message=f"file does not parse: {src.parse_error}",
                )
            )
    for rule in rules:
        findings.extend(rule.check_project(project))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def render_report(findings: List[Finding], as_json: bool) -> Tuple[str, int]:
    """(report text, exit code). Exit 1 iff any UNSUPPRESSED finding."""
    active = [f for f in findings if not f.suppressed]
    if as_json:
        payload = {
            "findings": [f.as_dict() for f in findings],
            "active": len(active),
            "suppressed": len(findings) - len(active),
        }
        return json.dumps(payload, indent=2), 1 if active else 0
    out = [f.render() for f in active]
    n_sup = len(findings) - len(active)
    out.append(
        f"raydp-lint: {len(active)} finding(s)"
        + (f", {n_sup} suppressed" if n_sup else "")
    )
    return "\n".join(out), 1 if active else 0


# ---------------------------------------------------------------------------
# shared AST helpers used by several rules
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
