"""RPC wire-surface extraction: every handler, every call site, one model.

The cluster speaks three stringly-typed planes, and nothing in the language
ties their two ends together — a renamed handler or a drifted kwarg fails at
runtime on a live cluster. This module extracts both ends statically so the
rpc-* rules (and the committed contract snapshot) can close the loop:

- **frame plane** — ``rpc(addr, ("op", {kwargs}))`` / ``rpc_pooled(...)`` /
  ``head_rpc("op", kw=...)`` request tuples, dispatched by the head/agent
  servers via ``getattr(obj, f"handle_{op}")(**kwargs)``. Servers are classes
  defining ≥2 ``handle_*`` methods (``handle_request`` is socketserver API,
  not an op). A literal ``("__obs__", ctx, request)`` trace envelope is
  unwrapped to the inner request, mirroring ``unwrap_traced``.
- **actor plane** — ``handle.<method>.remote(...)`` (optionally through
  ``.options(no_reply=..., timeout=...)``) ships a ``(method, args, kwargs,
  no_reply)`` frame applied as ``getattr(instance, method)(*args, **kwargs)``.
  The wire-reachable server surface is the PUBLIC method set of classes that
  are actually ``spawn()``-ed somewhere in the project
  (``ActorHandle.__getattr__`` refuses leading underscores, so ``_private``
  methods are not protocol). Direct ``_call("m", ...)`` / ``_try_send(addr,
  "m", ...)`` invocations with a literal method string are the same plane.
- **doorbell plane** — dunder transport ops (``__ping__``, ``__shutdown__``)
  the actor server answers itself, before user dispatch: a handler is an
  ``method == "__op__"`` comparison in a server loop, a call site is a
  literal 4-tuple frame whose op is dunder-named.

The extraction also records every ``<timeout-ish> or <default>`` expression
(the idiom silently maps an explicit ``timeout=0`` to the default — use
``default if timeout is None else timeout``), which rpc-closure reports as a
lint note.

Memoized per :class:`Project` via ``Project.rpc_surface()`` (sibling to
``surfaces()`` and ``get_lock_model``): four rules and the contract gate
share one walk.

The committed contract (``tools/analyze/rpc_contract.json``) serializes op →
handler signatures + caller files, WITHOUT line numbers — it changes only
when the wire surface itself changes, and ``--check-contract`` fails CI when
that happens without a contract edit in the same diff. ``--rpc-table`` emits
the human-readable surface table for docs/cluster.md (this one carries
``file:line`` anchors; regenerate with ``--write-rpc-table``).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.analyze.core import (
    Project,
    SourceFile,
    call_name,
    const_str,
    dotted_name,
)

OBS_FRAME_MARK = "__obs__"
CONTRACT_FILE = os.path.join("tools", "analyze", "rpc_contract.json")

#: frame-plane send helpers; ``head_rpc`` eats its own ``timeout`` kwarg
FRAME_SEND_NAMES = ("rpc", "rpc_pooled")
HEAD_RPC_NAME = "head_rpc"


def own_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Nodes of ``fn``'s body excluding nested def/lambda bodies: closures
    run later (often on another thread via ``threading.Thread``), so their
    contents are not part of the function's own synchronous execution."""
    stack = list(getattr(fn, "body", []))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _returns_value(fn: ast.AST) -> bool:
    """Does the function return anything a caller could USE? Bare constants
    (``return True`` / ``"pong"``) are acks a ``no_reply`` send may drop;
    any non-constant return expression is a meaningful reply."""
    for node in own_nodes(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            if not isinstance(node.value, ast.Constant):
                return True
    return False


def _has_yield(fn: ast.AST) -> bool:
    return any(
        isinstance(n, (ast.Yield, ast.YieldFrom)) for n in own_nodes(fn)
    )


def _signature(
    fn: ast.FunctionDef, drop_self: bool = True
) -> Tuple[List[str], List[str], bool, bool]:
    """(required, optional, has_var_args, has_var_kw) with ``self`` dropped."""
    args = fn.args
    names = [a.arg for a in (args.args[1:] if drop_self else args.args)]
    n_def = len(args.defaults)
    required = names[: len(names) - n_def] if n_def else list(names)
    optional = names[len(names) - n_def:] if n_def else []
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        (optional if d is not None else required).append(a.arg)
    return required, optional, args.vararg is not None, args.kwarg is not None


@dataclasses.dataclass
class Handler:
    """One server-side endpoint (any plane)."""

    plane: str  # "frame" | "actor" | "doorbell"
    op: str
    cls: str
    src: SourceFile
    node: ast.AST
    required: List[str] = dataclasses.field(default_factory=list)
    optional: List[str] = dataclasses.field(default_factory=list)
    has_var_args: bool = False
    has_var_kw: bool = False
    returns_value: bool = False
    has_yield: bool = False

    def binds_kwargs(self, kwargs: Set[str]) -> bool:
        """Frame plane: the server applies ``fn(**kwargs)``."""
        accepted = set(self.required) | set(self.optional)
        if not self.has_var_kw and not kwargs <= accepted:
            return False
        return set(self.required) <= kwargs

    def binds_call(self, n_pos: int, kwnames: Set[str]) -> bool:
        """Actor plane: the server applies ``fn(*args, **kwargs)``."""
        params = list(self.required) + list(self.optional)
        if not self.has_var_args and n_pos > len(params):
            return False
        positional = set(params[:n_pos])
        if not self.has_var_kw and not kwnames <= set(params) - positional:
            return False
        return set(self.required) <= positional | kwnames

    def signature(self) -> str:
        parts = list(self.required) + [f"{o}=…" for o in self.optional]
        if self.has_var_args:
            parts.append("*a")
        if self.has_var_kw:
            parts.append("**kw")
        name = f"handle_{self.op}" if self.plane == "frame" else self.op
        owner = f"{self.cls}." if self.cls else ""
        return f"{owner}{name}({', '.join(parts)})"

    def contract_entry(self) -> dict:
        """Line-number-free serialization: stable under unrelated edits."""
        return {
            "cls": self.cls,
            "path": self.src.display_path,
            "required": list(self.required),
            "optional": list(self.optional),
            "var_args": self.has_var_args,
            "var_kw": self.has_var_kw,
            "returns_value": self.returns_value,
        }


@dataclasses.dataclass
class CallSite:
    """One client-side invocation (any plane)."""

    plane: str
    op: str
    src: SourceFile
    node: ast.AST
    via: str  # rpc | rpc_pooled | head_rpc | remote | _call | _try_send | frame
    n_pos: int = 0  # actor plane; -1 = *spread (unknowable)
    kwargs: Optional[Set[str]] = None  # None = not statically known
    no_reply: bool = False
    payloads: List[ast.AST] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class TimeoutOrSite:
    """A ``<timeout-ish> or <default>`` expression."""

    src: SourceFile
    node: ast.AST
    func_name: str
    name: str  # the timeout-ish left operand, e.g. "timeout"/"self._timeout"


@dataclasses.dataclass
class RpcSurface:
    frame_handlers: Dict[str, List[Handler]]
    actor_classes: Set[str]  # class names seen as spawn()'s first argument
    actor_handlers: Dict[str, List[Handler]]  # public methods of spawned classes
    class_methods: Dict[str, List[Handler]]  # every project class (fallback)
    doorbell_handlers: Dict[str, List[Handler]]
    calls: List[CallSite]
    timeout_or_sites: List[TimeoutOrSite]

    def calls_on(self, plane: str) -> List[CallSite]:
        return [c for c in self.calls if c.plane == plane]


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------


def _collect_frame_handlers(project: Project) -> Dict[str, List[Handler]]:
    handlers: Dict[str, List[Handler]] = {}
    for src in project:
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            methods = [
                m
                for m in node.body
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                and m.name.startswith("handle_")
                and m.name != "handle_request"  # socketserver API, not an op
            ]
            if len(methods) < 2:
                continue
            for m in methods:
                required, optional, var_args, var_kw = _signature(m)
                op = m.name[len("handle_"):]
                handlers.setdefault(op, []).append(
                    Handler(
                        plane="frame",
                        op=op,
                        cls=node.name,
                        src=src,
                        node=m,
                        required=required,
                        optional=optional,
                        has_var_args=var_args,
                        has_var_kw=var_kw,
                        returns_value=_returns_value(m),
                        has_yield=_has_yield(m),
                    )
                )
    return handlers


def _collect_spawned_classes(project: Project) -> Set[str]:
    """Class names passed as the first positional argument to ``spawn(...)``
    / ``cluster.spawn(...)`` — the only classes the actor wire can reach."""
    spawned: Set[str] = set()
    for src in project:
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            name = call_name(node)
            if name is None or name.rsplit(".", 1)[-1] != "spawn":
                continue
            target = dotted_name(node.args[0])
            if target is not None:
                spawned.add(target.rsplit(".", 1)[-1])
    return spawned


def _method_handler(plane: str, cls: ast.ClassDef, m, src: SourceFile) -> Handler:
    required, optional, var_args, var_kw = _signature(m)
    return Handler(
        plane=plane,
        op=m.name,
        cls=cls.name,
        src=src,
        node=m,
        required=required,
        optional=optional,
        has_var_args=var_args,
        has_var_kw=var_kw,
        returns_value=_returns_value(m),
        has_yield=_has_yield(m),
    )


def _collect_class_methods(
    project: Project, spawned: Set[str]
) -> Tuple[Dict[str, List[Handler]], Dict[str, List[Handler]]]:
    """(actor_handlers, class_methods): the former is the wire-reachable
    surface (public methods of spawned classes), the latter every method on
    every project class — the closure fallback, so a dispatch on a handle
    whose spawn site is out of scan scope is not a false 'unknown'."""
    actor: Dict[str, List[Handler]] = {}
    every: Dict[str, List[Handler]] = {}
    for src in project:
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for m in node.body:
                if not isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                h = _method_handler("actor", node, m, src)
                every.setdefault(m.name, []).append(h)
                if node.name in spawned and not m.name.startswith("_"):
                    actor.setdefault(m.name, []).append(h)
    return actor, every


def _collect_doorbell_handlers(project: Project) -> Dict[str, List[Handler]]:
    """``method == "__op__"`` comparisons in a server loop: the transport
    answers these before user dispatch (worker.py's ping/shutdown doorbell)."""
    handlers: Dict[str, List[Handler]] = {}
    for src in project:
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Compare) or len(node.ops) != 1:
                continue
            if not isinstance(node.ops[0], ast.Eq):
                continue
            left = dotted_name(node.left)
            if left is None or left.rsplit(".", 1)[-1] != "method":
                continue
            op = const_str(node.comparators[0])
            if op is None or not (op.startswith("__") and op.endswith("__")):
                continue
            handlers.setdefault(op, []).append(
                Handler(plane="doorbell", op=op, cls="", src=src, node=node)
            )
    return handlers


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------


def _frame_request(node: ast.AST) -> Optional[Tuple[str, Optional[Set[str]], List[ast.AST]]]:
    """(op, kwargs-or-None, payload exprs) from a literal request tuple,
    unwrapping a literal trace envelope; None when the shape is not the
    named-op plane (actor 4-tuples and friends are out of scope here)."""
    if not isinstance(node, ast.Tuple):
        return None
    elts = node.elts
    if len(elts) == 3 and const_str(elts[0]) == OBS_FRAME_MARK:
        return _frame_request(elts[2])
    if len(elts) != 2:
        return None
    op = const_str(elts[0])
    if op is None:
        return None
    kw_node = elts[1]
    if isinstance(kw_node, ast.Dict):
        keys: Set[str] = set()
        payloads: List[ast.AST] = []
        for k, v in zip(kw_node.keys, kw_node.values):
            if k is None:  # **spread — arity unknowable, values still checkable
                return op, None, list(kw_node.values)
            ks = const_str(k)
            if ks is None:
                return op, None, list(kw_node.values)
            keys.add(ks)
            payloads.append(v)
        return op, keys, payloads
    return op, None, []


def _keyword_flag(node: ast.Call, name: str) -> bool:
    for kw in node.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


def _collect_calls(project: Project) -> List[CallSite]:
    calls: List[CallSite] = []
    for src in project:
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            # doorbell: a literal 4-tuple frame with a dunder op
            if isinstance(node, ast.Tuple) and len(node.elts) == 4:
                op = const_str(node.elts[0])
                if op and op.startswith("__") and op.endswith("__"):
                    calls.append(
                        CallSite("doorbell", op, src, node, via="frame")
                    )
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            last = name.rsplit(".", 1)[-1] if name else None
            if last in FRAME_SEND_NAMES and len(node.args) >= 2:
                req = _frame_request(node.args[1])
                if req is not None:
                    op, kwargs, payloads = req
                    calls.append(
                        CallSite(
                            "frame", op, src, node, via=last,
                            kwargs=kwargs, payloads=payloads,
                        )
                    )
            elif last == HEAD_RPC_NAME and node.args:
                op = const_str(node.args[0])
                if op is None:
                    continue
                kwargs: Optional[Set[str]] = set()
                payloads = []
                for kw in node.keywords:
                    if kw.arg is None:  # **spread
                        kwargs = None
                        payloads.append(kw.value)
                        continue
                    if kw.arg == "timeout":  # consumed by the helper itself
                        continue
                    if kwargs is not None:
                        kwargs.add(kw.arg)
                    payloads.append(kw.value)
                calls.append(
                    CallSite(
                        "frame", op, src, node, via=last,
                        kwargs=kwargs, payloads=payloads,
                    )
                )
            elif last in ("_call", "_try_send") and node.args:
                # ActorHandle._call("m", args, kwargs, ...) /
                # _try_send(sock_path, "m", ...): the method name is the
                # first (resp. second) positional argument
                op_node = node.args[0] if last == "_call" else (
                    node.args[1] if len(node.args) > 1 else None
                )
                op = const_str(op_node) if op_node is not None else None
                if op is not None:
                    calls.append(
                        CallSite(
                            "actor", op, src, node, via=last,
                            n_pos=-1, kwargs=None,
                            no_reply=_keyword_flag(node, "no_reply"),
                        )
                    )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "remote"
            ):
                inner = node.func.value
                no_reply = False
                if (
                    isinstance(inner, ast.Call)
                    and isinstance(inner.func, ast.Attribute)
                    and inner.func.attr == "options"
                ):
                    no_reply = _keyword_flag(inner, "no_reply")
                    inner = inner.func.value
                if not isinstance(inner, ast.Attribute):
                    continue  # bare .remote() on a name: not this plane
                kwnames: Optional[Set[str]] = set()
                payloads = list(node.args)
                for kw in node.keywords:
                    payloads.append(kw.value)
                    if kw.arg is None:
                        kwnames = None
                    elif kwnames is not None:
                        kwnames.add(kw.arg)
                n_pos = len(node.args)
                if any(isinstance(a, ast.Starred) for a in node.args):
                    n_pos = -1
                calls.append(
                    CallSite(
                        "actor", inner.attr, src, node, via="remote",
                        n_pos=n_pos, kwargs=kwnames, no_reply=no_reply,
                        payloads=payloads,
                    )
                )
    return calls


# ---------------------------------------------------------------------------
# timeout `or`-default idiom
# ---------------------------------------------------------------------------


def _timeoutish(name: Optional[str]) -> bool:
    if name is None:
        return False
    return "timeout" in name.rsplit(".", 1)[-1]


def _collect_timeout_or(project: Project) -> List[TimeoutOrSite]:
    sites: List[TimeoutOrSite] = []
    for src in project:
        if src.tree is None:
            continue
        func_stack: List[str] = []

        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func_stack.append(node.name)
                for child in ast.iter_child_nodes(node):
                    visit(child)
                func_stack.pop()
                return
            if isinstance(node, ast.BoolOp) and isinstance(node.op, ast.Or):
                left = dotted_name(node.values[0])
                if _timeoutish(left):
                    sites.append(
                        TimeoutOrSite(
                            src, node,
                            func_stack[-1] if func_stack else "<module>",
                            left,
                        )
                    )
            for child in ast.iter_child_nodes(node):
                visit(child)

        visit(src.tree)
    return sites


# ---------------------------------------------------------------------------
# assembly + memoization
# ---------------------------------------------------------------------------


def extract(project: Project) -> RpcSurface:
    frame_handlers = _collect_frame_handlers(project)
    spawned = _collect_spawned_classes(project)
    actor_handlers, class_methods = _collect_class_methods(project, spawned)
    return RpcSurface(
        frame_handlers=frame_handlers,
        actor_classes=spawned,
        actor_handlers=actor_handlers,
        class_methods=class_methods,
        doorbell_handlers=_collect_doorbell_handlers(project),
        calls=_collect_calls(project),
        timeout_or_sites=_collect_timeout_or(project),
    )


def get_rpc_surface(project: Project) -> RpcSurface:
    """Memoized per project (four rules + the contract gate share it)."""
    surface = getattr(project, "_rpc_surface", None)
    if surface is None:
        surface = extract(project)
        project._rpc_surface = surface  # type: ignore[attr-defined]
    return surface


# ---------------------------------------------------------------------------
# contract snapshot
# ---------------------------------------------------------------------------


def build_contract(surface: RpcSurface) -> dict:
    """Line-number-free wire-surface snapshot: op → handler signatures +
    caller files, per plane. Changes exactly when the protocol changes."""
    contract: dict = {"version": 1, "frame": {}, "actor": {}, "doorbell": {}}
    callers: Dict[Tuple[str, str], Set[str]] = {}
    for call in surface.calls:
        callers.setdefault((call.plane, call.op), set()).add(
            call.src.display_path
        )
    for op, hs in surface.frame_handlers.items():
        contract["frame"][op] = {
            "handlers": sorted(
                (h.contract_entry() for h in hs),
                key=lambda e: (e["path"], e["cls"]),
            ),
            "callers": sorted(callers.get(("frame", op), ())),
        }
    # actor plane: the wire-reachable surface is the spawned classes' public
    # methods; dispatched ops resolved only through the fallback inventory
    # (spawn site out of scope) still enter the contract via their callers
    actor_ops = set(surface.actor_handlers)
    actor_ops.update(
        op for (plane, op) in callers if plane == "actor"
    )
    for op in actor_ops:
        hs = surface.actor_handlers.get(op, [])
        contract["actor"][op] = {
            "handlers": sorted(
                (h.contract_entry() for h in hs),
                key=lambda e: (e["path"], e["cls"]),
            ),
            "callers": sorted(callers.get(("actor", op), ())),
        }
    for op, hs in surface.doorbell_handlers.items():
        contract["doorbell"][op] = {
            "handlers": sorted(
                ({"path": h.src.display_path} for h in hs),
                key=lambda e: e["path"],
            ),
            "callers": sorted(callers.get(("doorbell", op), ())),
        }
    return contract


def render_contract(contract: dict) -> str:
    return json.dumps(contract, indent=2, sort_keys=True) + "\n"


def check_contract(surface: RpcSurface, committed: dict) -> List[str]:
    """Human-readable mismatches between the live surface and the committed
    contract (empty = in sync). Every line names the op and the fix."""
    problems: List[str] = []
    live = build_contract(surface)
    for plane in ("frame", "actor", "doorbell"):
        live_ops = live.get(plane, {})
        committed_ops = committed.get(plane, {})
        for op in sorted(set(live_ops) - set(committed_ops)):
            problems.append(
                f"{plane} op '{op}' exists in the tree but not in the "
                "committed contract — run --write-contract and commit the diff"
            )
        for op in sorted(set(committed_ops) - set(live_ops)):
            problems.append(
                f"{plane} op '{op}' is in the committed contract but no "
                "longer in the tree — run --write-contract and commit the diff"
            )
        for op in sorted(set(live_ops) & set(committed_ops)):
            if live_ops[op] != committed_ops[op]:
                problems.append(
                    f"{plane} op '{op}' drifted from the committed contract "
                    "(signature or caller set changed) — run --write-contract "
                    "and commit the diff"
                )
    return problems


# ---------------------------------------------------------------------------
# docs table
# ---------------------------------------------------------------------------

RPC_TABLE_BEGIN = "<!-- rpc-surface:begin (generated: python -m tools.analyze --write-rpc-table) -->"
RPC_TABLE_END = "<!-- rpc-surface:end -->"


def render_rpc_table(surface: RpcSurface) -> str:
    """Markdown table op → caller files → handler ``file:line`` (frame +
    doorbell planes, plus dispatched actor ops — the actual wire traffic)."""
    callers: Dict[Tuple[str, str], Set[str]] = {}
    for call in surface.calls:
        callers.setdefault((call.plane, call.op), set()).add(
            call.src.display_path
        )
    rows: List[Tuple[str, str, str, str]] = []
    for op, hs in surface.frame_handlers.items():
        rows.append(("frame", op, *_table_cells(hs, callers.get(("frame", op)))))
    for op, hs in surface.doorbell_handlers.items():
        rows.append(
            ("doorbell", op, *_table_cells(hs, callers.get(("doorbell", op))))
        )
    for (plane, op), files in callers.items():
        if plane != "actor":
            continue
        hs = surface.actor_handlers.get(op) or surface.class_methods.get(op, [])
        rows.append(("actor", op, *_table_cells(hs, files)))
    rows.sort()
    lines = [
        "| plane | op | caller files | handler |",
        "|---|---|---|---|",
    ]
    for plane, op, caller_cell, handler_cell in rows:
        lines.append(f"| {plane} | `{op}` | {caller_cell} | {handler_cell} |")
    return "\n".join(lines)


def _table_cells(
    handlers: List[Handler], caller_files: Optional[Set[str]]
) -> Tuple[str, str]:
    caller_cell = (
        "<br>".join(f"`{p}`" for p in sorted(caller_files))
        if caller_files
        else "—"
    )
    handler_cell = (
        "<br>".join(
            f"`{h.src.display_path}:{h.node.lineno}`"
            + (f" `{h.cls}`" if h.cls else "")
            for h in handlers
        )
        if handlers
        else "—"
    )
    return caller_cell, handler_cell
