"""rpc-closure: the wire surface is closed — every send has a handler,
every handler a sender, and every call shape binds its signature.

Built on the project-wide RPC surface (:mod:`tools.analyze.rpc`), which
covers all three planes; ``rpc-protocol`` (the v1 rule) keeps its original
frame/actor checks, and this rule extends closure to the full extracted
surface:

- **unknown op** — a frame call whose op no server handles, an actor
  dispatch no project class defines, or a doorbell frame no server loop
  answers: the call fails at runtime with a stringly-typed AttributeError.
- **dead wire surface** — a frame ``handle_*`` or doorbell op with no
  statically-visible sender. Dead FRAME/DOORBELL surface only: actor-plane
  methods are also ordinary Python methods callable in-process, so a
  no-``.remote``-site method is not evidence of dead protocol. Suppress on
  the handler line for operator/debug surfaces exercised only reflectively.
- **arity/kwarg mismatch** — a frame call whose literal kwargs no handler
  binds (``**kwargs``-tolerant handlers accept anything), or an actor
  dispatch whose positional/keyword shape the SPAWNED target class cannot
  bind (when exactly one spawned class defines the method; ambiguous names
  and ``*``-spreads are skipped — under-reporting beats mis-attributing).
- **timeout ``or``-default idiom** (lint note) — ``timeout or 300.0`` maps
  an explicit ``timeout=0`` to the default; write
  ``300.0 if timeout is None else timeout``.

The committed contract snapshot (``rpc_contract.json``, ``--check-contract``)
gates the same surface in CI: this rule closes it within a revision, the
contract pins it across revisions.
"""

from __future__ import annotations

from typing import List, Set

from tools.analyze.core import Finding, Project


class RpcClosureRule:
    """Wire-surface closure: unknown ops, dead handlers, arity mismatches,
    and the timeout `or`-default idiom, across all three RPC planes."""

    name = "rpc-closure"

    def check_project(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        surface = project.rpc_surface()
        self._check_frame(surface, findings)
        self._check_actor(surface, findings)
        self._check_doorbell(surface, findings)
        for site in surface.timeout_or_sites:
            findings.append(
                site.src.finding(
                    self.name, site.node,
                    f"`{site.name} or <default>` in {site.func_name} maps an "
                    "explicit 0/falsy timeout to the default — use "
                    f"`<default> if {site.name} is None else {site.name}`",
                )
            )
        return findings

    def _check_frame(self, surface, findings: List[Finding]) -> None:
        handlers = surface.frame_handlers
        if not handlers:
            # nothing serves the frame plane in this scan (fixture subset):
            # call sites alone cannot be validated
            return
        called: Set[str] = set()
        for site in surface.calls_on("frame"):
            called.add(site.op)
            cands = handlers.get(site.op)
            if not cands:
                findings.append(
                    site.src.finding(
                        self.name, site.node,
                        f"unknown frame op '{site.op}': no handle_{site.op} "
                        "on any protocol server",
                    )
                )
                continue
            if site.kwargs is not None and not any(
                h.binds_kwargs(site.kwargs) for h in cands
            ):
                sigs = "; ".join(h.signature() for h in cands)
                sent = ", ".join(sorted(site.kwargs)) or "<none>"
                findings.append(
                    site.src.finding(
                        self.name, site.node,
                        f"frame op '{site.op}' arity mismatch: call sends "
                        f"({sent}) but no handler binds it — {sigs}",
                    )
                )
        for op, hs in sorted(handlers.items()):
            if op in called:
                continue
            for h in hs:
                findings.append(
                    h.src.finding(
                        self.name, h.node,
                        f"dead wire surface: {h.cls}.handle_{op} has no "
                        "statically-visible rpc/rpc_pooled/head_rpc sender",
                    )
                )

    def _check_actor(self, surface, findings: List[Finding]) -> None:
        if not surface.class_methods:
            return
        for site in surface.calls_on("actor"):
            spawned = surface.actor_handlers.get(site.op)
            cands = spawned or surface.class_methods.get(site.op)
            if not cands:
                findings.append(
                    site.src.finding(
                        self.name, site.node,
                        f"unknown actor method '{site.op}': no project class "
                        "defines it",
                    )
                )
                continue
            if (
                not spawned
                or len(spawned) != 1
                or site.n_pos < 0
                or site.kwargs is None
            ):
                continue  # ambiguous target or spread args: arity unknowable
            h = spawned[0]
            if not h.binds_call(site.n_pos, site.kwargs):
                sent = ", ".join(
                    [f"<{site.n_pos} positional>"] + sorted(site.kwargs)
                )
                findings.append(
                    site.src.finding(
                        self.name, site.node,
                        f"actor arity mismatch for '{site.op}': call sends "
                        f"({sent}) but {h.signature()} cannot bind it",
                    )
                )

    def _check_doorbell(self, surface, findings: List[Finding]) -> None:
        handlers = surface.doorbell_handlers
        called = {s.op for s in surface.calls_on("doorbell")}
        for site in surface.calls_on("doorbell"):
            if handlers and site.op not in handlers:
                findings.append(
                    site.src.finding(
                        self.name, site.node,
                        f"unknown doorbell op '{site.op}': no server loop "
                        "answers it",
                    )
                )
        for op, hs in sorted(handlers.items()):
            if op in called:
                continue
            for h in hs:
                findings.append(
                    h.src.finding(
                        self.name, h.node,
                        f"dead doorbell surface: '{op}' is answered here but "
                        "no statically-visible frame sends it",
                    )
                )
