"""blocking-under-lock: blocking operations executed while a lock is held.

The head SERVES pooled control-plane RPC and ISSUES RPCs; holding its lock
across a blocking call turns one slow peer into a frozen control plane — and
two processes doing it to each other is a distributed deadlock no
single-process lock graph can see. The rule flags, while any known lock is
held (lexically, or via a ``# guarded-by: <lock> held`` annotation):

- control-plane RPCs: ``rpc(...)``, ``rpc_pooled(...)``, ``head_rpc(...)``;
- socket sends/receives (``.sendall``/``.sendto``/``.recv``/``.recv_into``/
  ``.recvfrom``/``.accept``);
- subprocess waits: ``subprocess.run/call/check_output/check_call``,
  ``.communicate(...)``;
- ``time.sleep(...)``;
- unbounded ``.wait()`` / ``.join()`` (no timeout — a lost notify parks the
  holder forever; Condition.wait() releases its OWN lock but an unbounded
  one still hangs the caller, and any OTHER held lock stays held);
- future ``.result(...)`` (an actor-call round trip);
- jax host synchronization: ``block_until_ready``/``device_get``
  (seconds-long device syncs).

Fix by moving the call off-lock (snapshot state under the lock, block
outside — see ``Head._unlink_objects``); suppress only with reasoning that
shows the blocking path takes no other lock and the hold is deliberate.
Lock identities resolve exactly as in ``lock-order`` (tools/analyze/locks).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from tools.analyze.core import Finding, Project, call_name
from tools.analyze.locks import (
    HeldStackWalker,
    _annotations,
    entry_held,
    get_lock_model,
    iter_class_functions,
    module_of,
)

_RPC_NAMES = {"rpc", "rpc_pooled", "head_rpc"}
_SOCKET_ATTRS = {"sendall", "sendto", "recv", "recv_into", "recvfrom", "accept"}
_SUBPROCESS_TERMINALS = {"communicate"}
_SUBPROCESS_DOTTED = {"run", "call", "check_output", "check_call"}
_JAX_BLOCKING = {"block_until_ready", "device_get"}


def _classify(node: ast.Call) -> Optional[str]:
    """A human-readable description of the blocking op, or None."""
    name = call_name(node)
    terminal = name.split(".")[-1] if name else None
    attr = node.func.attr if isinstance(node.func, ast.Attribute) else None
    no_args = not node.args and not node.keywords
    if terminal in _RPC_NAMES:
        return f"control-plane RPC '{terminal}(...)'"
    if attr in _SOCKET_ATTRS:
        return f"socket '.{attr}(...)'"
    if attr in _SUBPROCESS_TERMINALS:
        return f"subprocess '.{attr}(...)'"
    if (
        name
        and terminal in _SUBPROCESS_DOTTED
        and len(name.split(".")) >= 2
        and name.split(".")[-2] == "subprocess"
    ):
        return f"'{name}(...)'"
    if terminal == "sleep" and (name in ("time.sleep", "sleep")):
        return "'time.sleep(...)'"
    if attr == "wait" and no_args:
        return "unbounded '.wait()' (no timeout: a lost notify hangs forever)"
    if attr == "join" and no_args:
        return "unbounded '.join()' (no timeout)"
    if attr == "result":
        return "future '.result(...)' (actor-call round trip)"
    if terminal in _JAX_BLOCKING:
        return f"jax '{terminal}(...)' (host-device sync)"
    return None


class _BlockWalker(HeldStackWalker):
    """Flag classified blocking calls while self.held is non-empty. The
    held-stack maintenance lives in HeldStackWalker."""

    def __init__(self, rule, src, model, annotations, class_name, module,
                 func_name, held, findings):
        super().__init__(
            src, model, annotations, class_name, module, func_name, held
        )
        self.rule = rule
        self.findings = findings

    def _clone(self, func_name, held):
        return _BlockWalker(
            self.rule, self.src, self.model, self.annotations,
            self.class_name, self.module, func_name, held, self.findings,
        )

    def visit_Call(self, node: ast.Call) -> None:
        if self.held:
            desc = _classify(node)
            if desc is not None:
                locks = ", ".join(
                    f"'{name}' ({site})" for name, site in self.held
                )
                self.findings.append(
                    self.src.finding(
                        self.rule.name,
                        node,
                        f"blocking {desc} in {self.func_name} while holding "
                        f"{locks} — move it off-lock (snapshot state under "
                        "the lock, block outside) or suppress with the "
                        "reasoning that makes the hold safe",
                    )
                )
        self.generic_visit(node)


class BlockingUnderLockRule:
    """Blocking calls (RPC, sleep, unbounded wait/join, subprocess, jax
    sync) made while a known lock is held."""

    name = "blocking-under-lock"

    def check_project(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        model = get_lock_model(project)
        for src in project:
            if src.tree is None:
                continue
            annotations = _annotations(src)
            module = module_of(src)
            for class_name, func in iter_class_functions(src.tree):
                held = entry_held(
                    func, annotations, model, class_name, module, src
                )
                walker = _BlockWalker(
                    self, src, model, annotations, class_name, module,
                    func.name, held, findings,
                )
                for stmt in func.body:
                    walker.visit(stmt)
        return findings
