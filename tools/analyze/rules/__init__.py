"""Rule registry. Adding a checker = one module with a Rule class + one
import line here (see docs/analysis.md "Adding a checker")."""

from tools.analyze.rules.blocking_under_lock import BlockingUnderLockRule
from tools.analyze.rules.conf_registry import ConfRegistryRule
from tools.analyze.rules.donation_aliasing import DonationAliasingRule
from tools.analyze.rules.env_registry import EnvRegistryRule
from tools.analyze.rules.except_order import ExceptOrderRule
from tools.analyze.rules.guarded_by import GuardedByRule
from tools.analyze.rules.lock_order import LockOrderRule
from tools.analyze.rules.metric_registry import MetricRegistryRule
from tools.analyze.rules.print_diagnostics import PrintDiagnosticsRule
from tools.analyze.rules.rpc_closure import RpcClosureRule
from tools.analyze.rules.rpc_error_safety import RpcErrorSafetyRule
from tools.analyze.rules.rpc_lock_flow import RpcLockFlowRule
from tools.analyze.rules.rpc_no_reply import RpcNoReplyRule
from tools.analyze.rules.rpc_payload_safety import RpcPayloadSafetyRule
from tools.analyze.rules.rpc_protocol import RpcProtocolRule
from tools.analyze.rules.swallowed_exceptions import SwallowedExceptionsRule

ALL_RULES = (
    DonationAliasingRule,
    RpcProtocolRule,
    SwallowedExceptionsRule,
    GuardedByRule,
    LockOrderRule,
    BlockingUnderLockRule,
    PrintDiagnosticsRule,
    MetricRegistryRule,
    ConfRegistryRule,
    EnvRegistryRule,
    RpcErrorSafetyRule,
    ExceptOrderRule,
    RpcClosureRule,
    RpcPayloadSafetyRule,
    RpcNoReplyRule,
    RpcLockFlowRule,
)


def rules_by_name():
    return {cls.name: cls for cls in ALL_RULES}
