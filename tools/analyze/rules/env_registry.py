"""env-registry: closure over the ``RAYDP_TPU_*`` environment surface.

Env vars are the widest-reaching knobs in the system — they cross process
boundaries (head -> zygote -> worker env dicts) and are set by operators who
only have the docs. Checks:

- **undocumented-env** — a ``RAYDP_TPU_*`` var read in code (os.getenv /
  environ.get / ``in os.environ`` / module-level ``FOO_ENV = "RAYDP_TPU_X"``
  constants resolved project-wide) is never mentioned in backticks anywhere
  under ``docs/`` or README.md. Full-surface sweeps only.
- **dead-env-doc** — a var documented in a docs table that no code reads
  *or* sets: stale rename. (Set-only vars are fine — spawners export vars
  their children read; doc rows for them are the contract.)

Inline backticked mentions count as documentation — the bar is "an operator
grepping the docs finds it", not "it is in one specific table".
Suppress doc-side findings with ``<!-- raydp-lint: disable=env-registry -->``.
"""

from __future__ import annotations

from typing import List

from tools.analyze.core import Finding, Project


class EnvRegistryRule:
    name = "env-registry"

    def check_project(self, project: Project) -> List[Finding]:
        surf = project.surfaces()
        findings: List[Finding] = []
        if not surf.full_surface:
            return findings

        documented = {d.name for d in surf.doc_envs}
        read_names = {e.name for e in surf.env_reads}
        set_names = {e.name for e in surf.env_sets}

        reported = set()
        for use in surf.env_reads:
            if use.name in documented or use.name in reported:
                continue
            reported.add(use.name)
            src = project.file(use.path)
            msg = (
                f"env var `{use.name}` is read here but never documented — "
                "mention it (backticked) in the owning docs page so "
                "operators can find it"
            )
            if src is not None:
                findings.append(src.finding(self.name, use.line, msg))
            else:
                findings.append(Finding(self.name, use.path, use.line, 0, msg))

        seen_doc = set()
        for entry in surf.doc_envs:
            if entry.name in read_names or entry.name in set_names:
                continue
            if entry.name in seen_doc:
                continue
            seen_doc.add(entry.name)
            doc = surf.doc_files.get(entry.path)
            suppressed = bool(doc and doc.is_suppressed(self.name, entry.line))
            findings.append(
                Finding(
                    self.name, entry.path, entry.line, 0,
                    f"docs mention env var `{entry.name}` but no code reads "
                    "or sets it — stale rename or dead knob",
                    suppressed=suppressed,
                )
            )
        return findings
