"""swallowed-exceptions: no silent ``except: pass``-shaped handlers.

The ``store._delete_blocks`` failures leaked blocks quietly until PR 3 added
``store.delete_failures`` — this rule makes that class structural: an except
handler whose body does nothing (``pass`` / ``continue`` / ``break`` / a bare
docstring) must either log through the structured logger, bump a metrics
counter, or carry an explicit ``# raydp-lint: disable=swallowed-exceptions``
suppression stating why swallowing is correct.

Handlers that do real work (return a fallback, set state, retry) are not
flagged — the target is the *silent* shape. ``ImportError`` /
``ModuleNotFoundError`` handlers are exempt: optional-dependency gating is
this repo's sanctioned use of quiet except (the container policy forbids
installing the missing package anyway).
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tools.analyze.core import Finding, Project, call_name

_LOG_SEGMENTS = {"log", "logger", "obs_log", "get_logger", "metrics", "warnings"}
_LOG_METHODS = {
    "info", "warning", "error", "exception", "debug", "warn",
    "inc", "observe", "set",
}
_IMPORT_ERRORS = {"ImportError", "ModuleNotFoundError"}


def _names_in_type(node: Optional[ast.AST]) -> List[str]:
    if node is None:
        return []
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.append(sub.id)
        elif isinstance(sub, ast.Attribute):
            out.append(sub.attr)
    return out


def _is_trivial_stmt(stmt: ast.stmt) -> bool:
    if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
        return True
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
        return True  # docstring / ellipsis
    return False


def _observes(handler: ast.ExceptHandler) -> bool:
    """Does the handler body log, count, or re-raise?"""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name is None:
                continue
            parts = name.split(".")
            if parts[-1] in _LOG_METHODS and (
                len(parts) == 1 or parts[-2] in _LOG_SEGMENTS or "log" in parts[-2]
            ):
                return True
            if any(p in _LOG_SEGMENTS for p in parts):
                return True
    return False


class SwallowedExceptionsRule:
    name = "swallowed-exceptions"

    def check_project(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in project:
            if src.tree is None:
                continue
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if not all(_is_trivial_stmt(s) for s in node.body):
                    continue
                if _observes(node):
                    continue
                type_names = _names_in_type(node.type)
                if type_names and set(type_names) <= _IMPORT_ERRORS:
                    continue
                caught = ", ".join(type_names) if type_names else "everything"
                findings.append(
                    src.finding(
                        self.name, node,
                        f"silently swallows {caught} — log via obs.log, bump "
                        "a metrics counter, or suppress with a stated reason",
                    )
                )
        return findings
