"""lock-order: lock-acquisition-order cycles (potential deadlocks).

The control plane holds 13+ locks and the head both SERVES pooled RPC and
ISSUES RPCs — the classic environment for lock-inversion deadlocks that no
test catches (the interleaving that deadlocks is the one CI never runs).
This rule builds the package-wide lock-acquisition order graph and flags any
cycle, with BOTH acquisition paths in the finding:

- lock identities resolve package-wide (self-attr locks, module globals,
  ``Condition``-wrapping pairs like ``head.actor_state_cond``/``head.lock``
  collapse to one node) — see tools/analyze/locks.py;
- edges come from lexical ``with <lockA>: ... with <lockB>`` nesting, plus
  interprocedural entry edges through ``# guarded-by: <lock> held``
  annotated functions (the function body acquires under the caller's lock);
- a pair of functions acquiring the same two locks in opposite orders is a
  2-cycle and reported with both sites; longer cycles are reported once per
  strongly-connected component with the full edge list.

The runtime counterpart is ``RAYDP_TPU_SANITIZE=lockdep``
(raydp_tpu/sanitize.py), which catches orders the static net cannot see
(locks passed through data structures, dynamic dispatch).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analyze.core import Finding, Project
from tools.analyze.locks import (
    HeldStackWalker,
    _annotations,
    entry_held,
    get_lock_model,
    iter_class_functions,
    module_of,
)


class _Edge:
    __slots__ = ("src", "node", "func", "holder_site", "acquire_site")

    def __init__(self, src, node, func, holder_site, acquire_site):
        self.src = src
        self.node = node  # AST node of the inner acquisition (anchor)
        self.func = func
        self.holder_site = holder_site
        self.acquire_site = acquire_site

    def describe(self, a: str, b: str) -> str:
        return (
            f"{a} -> {b} {self.acquire_site} "
            f"(outer lock {self.holder_site})"
        )


class _AcqWalker(HeldStackWalker):
    """Collect (held -> acquired) edges from one function body. The held
    stack, reentrancy skip, multi-item `with a, b:` sequencing, and nested
    def/lambda context reset all live in HeldStackWalker."""

    def __init__(self, rule, src, model, annotations, class_name, module,
                 func_name, held):
        super().__init__(
            src, model, annotations, class_name, module, func_name, held
        )
        self.rule = rule

    def _clone(self, func_name, held):
        return _AcqWalker(
            self.rule, self.src, self.model, self.annotations,
            self.class_name, self.module, func_name, held,
        )

    def on_acquire(self, canonical: str, node: ast.With) -> None:
        for holder, holder_site in self.held:
            self.rule.add_edge(
                holder,
                canonical,
                _Edge(
                    self.src, node, self.func_name, holder_site,
                    self._acquire_site(node),
                ),
            )


class LockOrderRule:
    """Cycles in the package-wide lock-acquisition order graph."""

    name = "lock-order"

    def __init__(self):
        self.edges: Dict[Tuple[str, str], _Edge] = {}

    def add_edge(self, a: str, b: str, edge: _Edge) -> None:
        self.edges.setdefault((a, b), edge)  # first site wins (deterministic)

    def check_project(self, project: Project) -> List[Finding]:
        self.edges = {}
        model = get_lock_model(project)
        for src in project:
            if src.tree is None:
                continue
            annotations = _annotations(src)
            module = module_of(src)
            for class_name, func in iter_class_functions(src.tree):
                held = entry_held(
                    func, annotations, model, class_name, module, src
                )
                walker = _AcqWalker(
                    self, src, model, annotations, class_name, module,
                    func.name, held,
                )
                for stmt in func.body:
                    walker.visit(stmt)
        return self._findings()

    # ---------- cycle detection ----------

    def _findings(self) -> List[Finding]:
        findings: List[Finding] = []
        reported_pairs: Set[Tuple[str, str]] = set()
        # 2-cycles: the same two locks taken in opposite orders
        for (a, b) in sorted(self.edges):
            if (b, a) not in self.edges or (b, a) in reported_pairs:
                continue
            reported_pairs.add((a, b))
            fwd, rev = self.edges[(a, b)], self.edges[(b, a)]
            anchor = min(
                (fwd, rev), key=lambda e: (e.src.display_path, e.node.lineno)
            )
            findings.append(
                anchor.src.finding(
                    self.name,
                    anchor.node,
                    f"lock-order inversion between '{a}' and '{b}' "
                    f"(potential deadlock): {fwd.describe(a, b)}; "
                    f"{rev.describe(b, a)} — flip one order, or suppress "
                    "with the reasoning that proves both paths can never "
                    "contend",
                )
            )
        # longer cycles: SCCs not already explained by a reported 2-cycle
        for scc in self._sccs():
            if len(scc) < 3:
                continue
            scc_set = set(scc)
            if any(
                a in scc_set and b in scc_set for (a, b) in reported_pairs
            ):
                continue
            cycle_edges = [
                (a, b) for (a, b) in sorted(self.edges)
                if a in scc_set and b in scc_set
            ]
            anchor = self.edges[cycle_edges[0]]
            path = "; ".join(
                self.edges[(a, b)].describe(a, b) for (a, b) in cycle_edges
            )
            findings.append(
                anchor.src.finding(
                    self.name,
                    anchor.node,
                    f"lock-order cycle across {len(scc)} locks "
                    f"({' -> '.join(sorted(scc_set))}) — potential deadlock: "
                    f"{path}",
                )
            )
        return findings

    def _sccs(self) -> List[List[str]]:
        """Tarjan SCCs over the acquisition graph (iterative)."""
        adj: Dict[str, List[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        for root in sorted(adj):
            if root in index:
                continue
            work = [(root, iter(adj[root]))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                node, it = work[-1]
                advanced = False
                for nxt in it:
                    if nxt not in index:
                        index[nxt] = low[nxt] = counter[0]
                        counter[0] += 1
                        stack.append(nxt)
                        on_stack.add(nxt)
                        work.append((nxt, iter(adj[nxt])))
                        advanced = True
                        break
                    if nxt in on_stack:
                        low[node] = min(low[node], index[nxt])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        popped = stack.pop()
                        on_stack.discard(popped)
                        scc.append(popped)
                        if popped == node:
                            break
                    if len(scc) > 1:
                        sccs.append(scc)
        return sccs
