"""rpc-lock-flow: no handler blocks on the wire while holding a named lock —
checked THROUGH the call graph, not just lexically.

The cross-process deadlock shape: head handler holds ``head.lock`` and RPCs
an agent; the agent's handler needs something from the head; both control
planes freeze. Runtime lockdep only sees it when it actually deadlocks, and
``blocking-under-lock`` only sees the LEXICAL case (an ``rpc(...)`` directly
inside the ``with self.lock:`` block). This rule marries the lock model
(:mod:`tools.analyze.locks`) to the extracted RPC surface
(:mod:`tools.analyze.rpc`) and flags the interprocedural case: an RPC
**handler** (frame plane, or a spawned class's wire-reachable method) that,
while a resolved lock is held, calls a helper which — transitively, through
same-file ``self.method()`` / module-function calls — performs an outbound
RPC (``rpc``/``rpc_pooled``/``head_rpc``), a socket send
(``.sendall``/``.sendto``/``send_frame``), or an unbounded cond-``wait()``.

Deliberate scope cuts (each avoids a class of false positives):

- depth ≥ 1 only — the direct lexical case is blocking-under-lock's finding;
  double-reporting would force double suppressions.
- nested defs/lambdas inside a callee do not count as that callee's outbound
  ops (the package idiom runs slow agent RPCs on daemon threads precisely to
  get them off-lock — see ``Head._spawn``/``_kill_proc``).
- an outbound op on a line already carrying a ``blocking-under-lock`` or
  ``rpc-lock-flow`` suppression is trusted (the reasoning there covers the
  callers too).
- call resolution is same-file only (``self.m()`` to the handler's class,
  bare ``f()`` to module functions); cross-file flow is out of scope —
  under-reporting beats mis-attributed deadlock reports.

Fix like ``Head._unlink_objects``: snapshot under the lock, send outside.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analyze.core import Finding, Project, SourceFile, call_name
from tools.analyze.locks import (
    HeldStackWalker,
    _annotations,
    entry_held,
    get_lock_model,
    iter_class_functions,
    module_of,
)
from tools.analyze.rpc import own_nodes

_RPC_NAMES = {"rpc", "rpc_pooled", "head_rpc"}
_SEND_ATTRS = {"sendall", "sendto"}
_SEND_FUNCS = {"send_frame"}


def _outbound_desc(node: ast.Call) -> Optional[str]:
    """Why this call talks to another process (or parks), else None."""
    name = call_name(node)
    terminal = name.rsplit(".", 1)[-1] if name else None
    attr = node.func.attr if isinstance(node.func, ast.Attribute) else None
    if terminal in _RPC_NAMES:
        return f"outbound RPC '{terminal}(...)'"
    if attr in _SEND_ATTRS:
        return f"socket send '.{attr}(...)'"
    if terminal in _SEND_FUNCS:
        return f"frame send '{terminal}(...)'"
    if attr == "wait" and not node.args and not node.keywords:
        return "unbounded '.wait()'"
    return None


class _CallGraph:
    """Per-file transitive outbound-op index over class methods and module
    functions. ``witness(key)`` is the first outbound op reachable from the
    function, as a chain description, or None."""

    def __init__(self, src: SourceFile):
        self.src = src
        # (class_or_None, name) -> funcdef
        self.functions: Dict[Tuple[Optional[str], str], ast.AST] = {}
        if src.tree is not None:
            for cls, fn in iter_class_functions(src.tree):
                self.functions.setdefault((cls, fn.name), fn)
        self._memo: Dict[Tuple[Optional[str], str], Optional[str]] = {}

    def _direct_outbound(self, fn: ast.AST) -> Optional[Tuple[str, int]]:
        for node in own_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            desc = _outbound_desc(node)
            if desc is None:
                continue
            line = getattr(node, "lineno", 0)
            if self.src.is_suppressed(
                "blocking-under-lock", line
            ) or self.src.is_suppressed("rpc-lock-flow", line):
                continue  # an already-reasoned hold covers its callers too
            return desc, line
        return None

    def callees(self, fn: ast.AST, cls: Optional[str]):
        """(key, callee_name) for same-file calls in fn's own body."""
        for node in own_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None:
                continue
            parts = name.split(".")
            if len(parts) == 2 and parts[0] == "self" and cls is not None:
                key = (cls, parts[1])
            elif len(parts) == 1:
                key = (None, parts[0])
            else:
                continue
            if key in self.functions:
                yield key, name

    def witness(self, key: Tuple[Optional[str], str], _stack=None) -> Optional[str]:
        """Chain description 'a() -> b() -> rpc(...) at file:line' when the
        function TRANSITIVELY reaches an outbound op, else None. Direct ops
        in the entry function itself are NOT its witness (depth ≥ 1 is the
        caller's concern; blocking-under-lock owns depth 0) — but they ARE
        once reached through a call edge."""
        if key in self._memo:
            return self._memo[key]
        if _stack is None:
            _stack = set()
        if key in _stack:
            return None  # recursion cycle
        fn = self.functions.get(key)
        if fn is None:
            return None
        _stack.add(key)
        result: Optional[str] = None
        direct = self._direct_outbound(fn)
        if direct is not None:
            desc, line = direct
            result = f"{desc} at {self.src.display_path}:{line}"
        else:
            for callee_key, callee_name in self.callees(fn, key[0]):
                inner = self.witness(callee_key, _stack)
                if inner is not None:
                    result = f"{callee_name}() -> {inner}"
                    break
        _stack.discard(key)
        self._memo[key] = result
        return result


class _FlowWalker(HeldStackWalker):
    """While any lock is held, flag calls whose same-file callee transitively
    performs an outbound op (the callee's own nested-thread bodies excluded)."""

    def __init__(self, rule, src, model, annotations, class_name, module,
                 func_name, held, findings, graph):
        super().__init__(
            src, model, annotations, class_name, module, func_name, held
        )
        self.rule = rule
        self.findings = findings
        self.graph = graph

    def _clone(self, func_name, held):
        return _FlowWalker(
            self.rule, self.src, self.model, self.annotations,
            self.class_name, self.module, func_name, held, self.findings,
            self.graph,
        )

    def visit_Call(self, node: ast.Call) -> None:
        if self.held and _outbound_desc(node) is None:
            name = call_name(node)
            if name is not None:
                parts = name.split(".")
                key = None
                if len(parts) == 2 and parts[0] == "self" and self.class_name:
                    key = (self.class_name, parts[1])
                elif len(parts) == 1:
                    key = (None, parts[0])
                if key is not None:
                    chain = self.graph.witness(key)
                    if chain is not None:
                        locks = ", ".join(
                            f"'{n}' ({site})" for n, site in self.held
                        )
                        self.findings.append(
                            self.src.finding(
                                self.rule.name, node,
                                f"handler {self.func_name} performs "
                                f"{name}() -> {chain} while holding {locks} "
                                "— snapshot under the lock, send outside "
                                "(the cross-process deadlock shape)",
                            )
                        )
        self.generic_visit(node)


class RpcLockFlowRule:
    """RPC handlers that reach an outbound RPC/socket send/cond-wait through
    helper calls while holding a named lock (interprocedural; the lexical
    case is blocking-under-lock's)."""

    name = "rpc-lock-flow"

    def check_project(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        surface = project.rpc_surface()
        model = get_lock_model(project)
        # entry points: frame handlers + spawned classes' wire-reachable
        # methods — the functions another PROCESS invokes
        entries: Dict[str, List] = {}
        for handlers in list(surface.frame_handlers.values()) + list(
            surface.actor_handlers.values()
        ):
            for h in handlers:
                entries.setdefault(h.src.display_path, []).append(h)
        graphs: Dict[str, _CallGraph] = {}
        seen: Set[int] = set()
        for path, handlers in entries.items():
            for h in handlers:
                if id(h.node) in seen:
                    continue
                seen.add(id(h.node))
                src = h.src
                if path not in graphs:
                    graphs[path] = _CallGraph(src)
                annotations = _annotations(src)
                module = module_of(src)
                held = entry_held(
                    h.node, annotations, model, h.cls or None, module, src
                )
                walker = _FlowWalker(
                    self, src, model, annotations, h.cls or None, module,
                    getattr(h.node, "name", h.op), held, findings,
                    graphs[path],
                )
                for stmt in h.node.body:
                    walker.visit(stmt)
        return findings
