"""metric-registry: two-way code<->docs closure over metric names.

PR 17's capacity planner and the serve autoscaler both steer on metric names
read back out of the registry (`serve.p99_ms`, `mem.pressure`,
`serve.decode.veto.slots`); rename the instrumentation site and the
controller silently reads zeros forever. Three checks:

- **undocumented-write** — a metric instrumented in code has no row in any
  docs metric table (``docs/observability.md`` et al). Only in full-surface
  sweeps (package + bench in scope), so linting one subdirectory doesn't
  demand the docs describe it.
- **dead-doc-row** — a docs metric row matches no instrumentation site: the
  doc describes a series nobody emits (usually a rename that forgot the
  docs). Full-surface only.
- **read-without-writer** — a metric *read* (``.value``/``.quantile``,
  ``query_metrics``, ledger dict-gets) whose name no instrumentation site
  can produce. Fan-out suffixes (``.p99``/``.max``/``.delta``...) are
  stripped before matching; ``tenant.<ns>.``-style dynamic prefixes unify
  via segment wildcards. Gated on the name's leading family having writers
  in scope, so partial sweeps and self-contained fixtures work.

Docs-side findings anchor to the markdown row; suppress with an HTML
comment on that row: ``<!-- raydp-lint: disable=metric-registry -->``.
"""

from __future__ import annotations

from typing import List

from tools.analyze.core import Finding, Project
from tools.analyze.surfaces import patterns_match, strip_fanout


class MetricRegistryRule:
    name = "metric-registry"

    def check_project(self, project: Project) -> List[Finding]:
        surf = project.surfaces()
        findings: List[Finding] = []

        def code_finding(use, message: str) -> None:
            src = project.file(use.path)
            if src is not None:
                findings.append(src.finding(self.name, use.line, message))
            else:
                findings.append(
                    Finding(self.name, use.path, use.line, 0, message)
                )

        def doc_finding(entry, message: str) -> None:
            doc = surf.doc_files.get(entry.path)
            suppressed = bool(
                doc and doc.is_suppressed(self.name, entry.line)
            )
            findings.append(
                Finding(self.name, entry.path, entry.line, 0, message,
                        suppressed=suppressed)
            )

        # ---- undocumented-write (full-surface only)
        if surf.full_surface:
            reported = set()
            for w in surf.metric_writes:
                if w.pattern in reported:
                    continue
                if not surf.is_documented_metric(w.pattern):
                    reported.add(w.pattern)
                    code_finding(
                        w,
                        f"metric `{w.pattern}` is instrumented here but has "
                        "no row in any docs metric table — document it in "
                        "docs/observability.md or the owning subsystem page",
                    )

            # ---- dead-doc-row
            for entry in surf.doc_metrics:
                if any(
                    patterns_match(entry.name, w.pattern)
                    for w in surf.metric_writes
                ):
                    continue
                # a row may describe a fan-out series of a real instrument
                base = strip_fanout(entry.name)
                if base != entry.name and any(
                    patterns_match(base, w.pattern)
                    for w in surf.metric_writes
                ):
                    continue
                doc_finding(
                    entry,
                    f"docs row describes metric `{entry.name}` but no "
                    "instrumentation site emits it — stale rename or dead "
                    "series; fix the name or drop the row",
                )

        # ---- read-without-writer
        families = surf.write_families()
        seen_reads = set()
        for r in list(surf.metric_reads) + list(surf.metric_mentions):
            key = (r.pattern, r.path, r.line)
            if key in seen_reads:
                continue
            seen_reads.add(key)
            family = r.pattern.split(".", 1)[0]
            if family not in families:
                # reads into a family with no writers in scope: partial
                # sweep or a foreign namespace — not this rule's call
                continue
            if surf.has_writer(r.pattern):
                continue
            code_finding(
                r,
                f"metric `{r.pattern}` is read here but no instrumentation "
                "site can produce it — the reader is steering on a series "
                "nobody writes (typo'd or renamed metric?)",
            )

        return findings
