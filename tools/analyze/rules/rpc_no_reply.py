"""rpc-no-reply: a fire-and-forget send must not discard a real reply.

``handle.method.options(no_reply=True).remote(...)`` (and a direct
``_call(..., no_reply=True)``) tells the actor server to skip the reply
frame entirely — the caller gets a ``_CompletedFuture`` whose ``.result()``
is always ``None``. That is correct for acks, but if the target method
computes and returns a value, the contract silently breaks: the caller
*thinks* it has a result channel and reads ``None`` forever, and the
breakage only shows where the value is finally used, far from the send.

The rule resolves every ``no_reply=True`` dispatch on the extracted surface
(:mod:`tools.analyze.rpc`) against its target: spawned classes' methods
first, any project class as fallback. A target whose body returns a
non-constant expression (bare ``return True``/``"pong"`` acks are fine to
drop) is flagged. Fix by converting to a replied call, changing the handler
to return nothing, or suppressing on the send line with the reasoning that
makes the dropped value intentional.

No current call site uses ``no_reply=True`` (audited in this PR — the
mechanism exists in ``RemoteMethod.options`` but nothing exercises it yet);
the rule pins the invariant for when one appears.
"""

from __future__ import annotations

from typing import List

from tools.analyze.core import Finding, Project


class RpcNoReplyRule:
    """`no_reply=True` sends targeting handlers whose return value is
    meaningful (a dropped reply is a silent contract break)."""

    name = "rpc-no-reply"

    def check_project(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        surface = project.rpc_surface()
        for site in surface.calls:
            if not site.no_reply:
                continue
            cands = surface.actor_handlers.get(site.op) or (
                surface.class_methods.get(site.op, [])
            )
            for h in cands:
                if not h.returns_value:
                    continue
                findings.append(
                    site.src.finding(
                        self.name, site.node,
                        f"no_reply=True send of '{site.op}' discards the "
                        f"return value of {h.signature()} "
                        f"({h.src.display_path}:{h.node.lineno}) — use a "
                        "replied call, or make the handler return nothing",
                    )
                )
        return findings
