"""conf-registry: every conf key read has a declared default and a doc row.

The conf surface is string-keyed (`configs.get("etl.fuse_stages", ...)`,
serve/config.py's ``get("max_batch_size", 8)`` wrapper, session.py's
``_flag``): a typo'd key silently yields the fallback, and a key with *no*
fallback is a latent KeyError/None in a remote process. Checks:

- **no-default** — a conf read site passes no explicit default and the
  wrapper it goes through declares none either.
- **undocumented-key** — a key read in code has no row in any docs conf
  table (full-surface sweeps only).
- **dead-doc-key** — a documented key no code reads: usually a rename that
  forgot the docs table (full-surface only). Env-var rows and metric rows in
  mixed tables are excluded by shape.

Docs-side findings suppress via ``<!-- raydp-lint: disable=conf-registry -->``
on the row.
"""

from __future__ import annotations

from typing import List

from tools.analyze.core import Finding, Project

# keys that are forwarded verbatim to an external system (spark-compat
# passthrough namespaces) — documented behavior is "whatever the engine
# does", so closure is not ours to check
_PASSTHROUGH_PREFIXES = ("spark.",)


def _passthrough(key: str) -> bool:
    return key.startswith(_PASSTHROUGH_PREFIXES)


class ConfRegistryRule:
    name = "conf-registry"

    def check_project(self, project: Project) -> List[Finding]:
        surf = project.surfaces()
        findings: List[Finding] = []

        def code_finding(read, message: str) -> None:
            src = project.file(read.path)
            if src is not None:
                findings.append(src.finding(self.name, read.line, message))
            else:
                findings.append(
                    Finding(self.name, read.path, read.line, 0, message)
                )

        doc_keys = surf.doc_conf_keys()
        read_keys = surf.conf_keys()
        # a key is "defaulted" if ANY read site declares a default — one
        # canonical read with a default plus bare re-reads elsewhere is the
        # repo's normal shape
        defaulted = {c.key for c in surf.conf_reads if c.has_default}

        seen = set()
        for read in surf.conf_reads:
            if _passthrough(read.key):
                continue
            site = (read.key, read.path, read.line)
            if site in seen:
                continue
            seen.add(site)
            if read.key not in defaulted:
                code_finding(
                    read,
                    f"conf key `{read.key}` is read with no explicit default "
                    "at any site — a missing key becomes None/KeyError in a "
                    "remote process; declare the default here",
                )
                defaulted.add(read.key)  # one finding per key, not per site
            if surf.full_surface and read.key not in doc_keys:
                code_finding(
                    read,
                    f"conf key `{read.key}` has no row in any docs conf "
                    "table — add it to the owning page's knob table",
                )
                doc_keys.add(read.key)  # one finding per key

        if surf.full_surface:
            env_doc_names = {d.name for d in surf.doc_envs}
            for entry in surf.doc_confs:
                if entry.name in read_keys or _passthrough(entry.name):
                    continue
                if entry.name in env_doc_names:
                    continue  # env row in a mixed knob table
                doc = surf.doc_files.get(entry.path)
                suppressed = bool(
                    doc and doc.is_suppressed(self.name, entry.line)
                )
                findings.append(
                    Finding(
                        self.name, entry.path, entry.line, 0,
                        f"docs table documents conf key `{entry.name}` but "
                        "no code reads it — stale rename or dead knob",
                        suppressed=suppressed,
                    )
                )

        return findings
