"""rpc-protocol: the by-name RPC plane stays closed over ops and arities.

Control-plane dispatch is stringly typed: a caller sends ``("op", {kwargs})``
(via ``rpc``/``rpc_pooled`` with a request tuple, or the ``head_rpc`` helper)
and a server resolves ``handle_<op>`` by name and applies ``fn(**kwargs)``.
Nothing ties the two ends together as the protocol grows every PR — a typo'd
op or a renamed handler parameter fails only at runtime, on whichever code
path finally exercises it.

This rule closes the loop statically:

- **server surface** — every class defining ≥2 ``handle_<op>`` methods is a
  protocol server (Head, NodeAgent); each method contributes an op plus its
  keyword signature.
- **call sites** — ``rpc(addr, ("op", {...}))`` / ``rpc_pooled(...)`` with a
  literal request tuple, and ``head_rpc("op", key=...)``. A literal
  ``("__obs__", ctx, request)`` trace envelope is unwrapped to the inner
  request, mirroring ``unwrap_traced``. 4-element tuples are the actor method
  protocol (dispatch on arbitrary user classes) and are out of scope.
- **checks** — ``unknown-op`` (call site no server handles), ``arity``
  (no server's ``handle_<op>`` binds the provided kwargs), ``dead-handler``
  (a handler no statically-visible call site reaches; suppress on the def
  line for ops exercised only by tests or reflectively).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from tools.analyze.core import Finding, Project, SourceFile, call_name, const_str

OBS_FRAME_MARK = "__obs__"


@dataclasses.dataclass
class _Handler:
    op: str
    cls: str
    src: SourceFile
    node: ast.AST
    required: List[str]
    optional: List[str]
    has_var_kw: bool

    def binds(self, kwargs: Set[str]) -> bool:
        accepted = set(self.required) | set(self.optional)
        if not self.has_var_kw and not kwargs <= accepted:
            return False
        return set(self.required) <= kwargs

    def signature(self) -> str:
        parts = list(self.required) + [f"{o}=…" for o in self.optional]
        if self.has_var_kw:
            parts.append("**kw")
        return f"{self.cls}.handle_{self.op}({', '.join(parts)})"


@dataclasses.dataclass
class _CallSite:
    op: str
    src: SourceFile
    node: ast.AST
    kwargs: Optional[Set[str]]  # None = not statically known


def _handler_signature(fn: ast.FunctionDef) -> Tuple[List[str], List[str], bool]:
    args = fn.args
    names = [a.arg for a in args.args[1:]]  # drop self
    n_defaults = len(args.defaults)
    required = names[: len(names) - n_defaults] if n_defaults else list(names)
    optional = names[len(names) - n_defaults:] if n_defaults else []
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        (optional if d is not None else required).append(a.arg)
    return required, optional, args.kwarg is not None


def _collect_handlers(project: Project) -> Dict[str, List[_Handler]]:
    handlers: Dict[str, List[_Handler]] = {}
    for src in project:
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            methods = [
                m
                for m in node.body
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                and m.name.startswith("handle_")
                and m.name != "handle_request"  # socketserver API, not an op
            ]
            if len(methods) < 2:
                continue
            for m in methods:
                required, optional, has_var_kw = _handler_signature(m)
                handlers.setdefault(m.name[len("handle_"):], []).append(
                    _Handler(
                        op=m.name[len("handle_"):],
                        cls=node.name,
                        src=src,
                        node=m,
                        required=required,
                        optional=optional,
                        has_var_kw=has_var_kw,
                    )
                )
    return handlers


def _request_from_tuple(node: ast.AST) -> Optional[Tuple[str, Optional[Set[str]]]]:
    """(op, kwargs or None) from a literal request tuple, unwrapping a
    literal trace envelope; None when the shape is not the named-op plane."""
    if not isinstance(node, ast.Tuple):
        return None
    elts = node.elts
    if len(elts) == 3 and const_str(elts[0]) == OBS_FRAME_MARK:
        return _request_from_tuple(elts[2])
    if len(elts) != 2:
        return None  # actor protocol 4-tuples and friends: out of scope
    op = const_str(elts[0])
    if op is None:
        return None
    kw_node = elts[1]
    if isinstance(kw_node, ast.Dict):
        keys: Set[str] = set()
        for k in kw_node.keys:
            if k is None:  # **spread — arity unknowable
                return op, None
            ks = const_str(k)
            if ks is None:
                return op, None
            keys.add(ks)
        return op, keys
    return op, None


def _collect_call_sites(project: Project) -> List[_CallSite]:
    sites: List[_CallSite] = []
    for src in project:
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None:
                continue
            last = name.rsplit(".", 1)[-1]
            if last in ("rpc", "rpc_pooled") and len(node.args) >= 2:
                req = _request_from_tuple(node.args[1])
                if req is not None:
                    sites.append(_CallSite(req[0], src, node, req[1]))
            elif last == "head_rpc" and node.args:
                op = const_str(node.args[0])
                if op is None:
                    continue
                kwargs: Optional[Set[str]] = set()
                for kw in node.keywords:
                    if kw.arg is None:  # **spread
                        kwargs = None
                        break
                    if kw.arg != "timeout":  # consumed by the helper itself
                        kwargs.add(kw.arg)
                sites.append(_CallSite(op, src, node, kwargs))
    return sites


class RpcProtocolRule:
    name = "rpc-protocol"

    def check_project(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        handlers = _collect_handlers(project)
        sites = _collect_call_sites(project)
        if not handlers:
            # nothing serves the named-op plane in this scan (e.g. a fixture
            # subset) — call sites alone cannot be validated
            return findings
        called_ops: Set[str] = set()
        for site in sites:
            called_ops.add(site.op)
            cands = handlers.get(site.op)
            if not cands:
                findings.append(
                    site.src.finding(
                        self.name, site.node,
                        f"unknown op '{site.op}': no handle_{site.op} on any "
                        "protocol server",
                    )
                )
                continue
            if site.kwargs is not None and not any(
                h.binds(site.kwargs) for h in cands
            ):
                sigs = "; ".join(h.signature() for h in cands)
                sent = ", ".join(sorted(site.kwargs)) or "<none>"
                findings.append(
                    site.src.finding(
                        self.name, site.node,
                        f"arity mismatch for op '{site.op}': call sends "
                        f"({sent}) but no handler binds it — {sigs}",
                    )
                )
        for op, hs in sorted(handlers.items()):
            if op in called_ops:
                continue
            for h in hs:
                findings.append(
                    h.src.finding(
                        self.name, h.node,
                        f"dead handler {h.cls}.handle_{op}: no statically-"
                        "visible rpc/head_rpc call site sends this op",
                    )
                )
        return findings
