"""rpc-protocol: the by-name RPC plane stays closed over ops and arities.

Control-plane dispatch is stringly typed: a caller sends ``("op", {kwargs})``
(via ``rpc``/``rpc_pooled`` with a request tuple, or the ``head_rpc`` helper)
and a server resolves ``handle_<op>`` by name and applies ``fn(**kwargs)``.
Nothing ties the two ends together as the protocol grows every PR — a typo'd
op or a renamed handler parameter fails only at runtime, on whichever code
path finally exercises it.

This rule closes the loop statically:

- **server surface** — every class defining ≥2 ``handle_<op>`` methods is a
  protocol server (Head, NodeAgent); each method contributes an op plus its
  keyword signature.
- **call sites** — ``rpc(addr, ("op", {...}))`` / ``rpc_pooled(...)`` with a
  literal request tuple, and ``head_rpc("op", key=...)``. A literal
  ``("__obs__", ctx, request)`` trace envelope is unwrapped to the inner
  request, mirroring ``unwrap_traced``. 4-element tuples are the actor method
  protocol (dispatch on arbitrary user classes) and are out of scope.
- **checks** — ``unknown-op`` (call site no server handles), ``arity``
  (no server's ``handle_<op>`` binds the provided kwargs), ``dead-handler``
  (a handler no statically-visible call site reaches; suppress on the def
  line for ops exercised only by tests or reflectively).

The rule also inventories the **actor-dispatch plane** — the by-name half of
``handle.<method>.remote(...)`` / ``handle.<method>.options(...).remote(...)``
calls (the surface ``run_plan``/``run_tasks``/``run_shuffle`` and the SPMD
worker ops ride). Handles are untyped (any spawned class), so the op
inventory is every method defined on any project class: a dispatched method
name no class defines is an ``unknown actor method`` finding, and when
exactly ONE project class defines it, the call's positional/keyword shape
must bind its signature (``actor arity mismatch``). The doorbell transport
and the location-lease head op added by the compiled-plan control plane are
covered by the same inventories (``object_lookup_lease`` via head_rpc;
doorbell rides the existing actor plane — no new wire shapes escape the
rule).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from tools.analyze.core import Finding, Project, SourceFile, call_name, const_str

OBS_FRAME_MARK = "__obs__"


@dataclasses.dataclass
class _Handler:
    op: str
    cls: str
    src: SourceFile
    node: ast.AST
    required: List[str]
    optional: List[str]
    has_var_kw: bool

    def binds(self, kwargs: Set[str]) -> bool:
        accepted = set(self.required) | set(self.optional)
        if not self.has_var_kw and not kwargs <= accepted:
            return False
        return set(self.required) <= kwargs

    def signature(self) -> str:
        parts = list(self.required) + [f"{o}=…" for o in self.optional]
        if self.has_var_kw:
            parts.append("**kw")
        return f"{self.cls}.handle_{self.op}({', '.join(parts)})"


@dataclasses.dataclass
class _CallSite:
    op: str
    src: SourceFile
    node: ast.AST
    kwargs: Optional[Set[str]]  # None = not statically known


def _handler_signature(fn: ast.FunctionDef) -> Tuple[List[str], List[str], bool]:
    args = fn.args
    names = [a.arg for a in args.args[1:]]  # drop self
    n_defaults = len(args.defaults)
    required = names[: len(names) - n_defaults] if n_defaults else list(names)
    optional = names[len(names) - n_defaults:] if n_defaults else []
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        (optional if d is not None else required).append(a.arg)
    return required, optional, args.kwarg is not None


def _collect_handlers(project: Project) -> Dict[str, List[_Handler]]:
    handlers: Dict[str, List[_Handler]] = {}
    for src in project:
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            methods = [
                m
                for m in node.body
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                and m.name.startswith("handle_")
                and m.name != "handle_request"  # socketserver API, not an op
            ]
            if len(methods) < 2:
                continue
            for m in methods:
                required, optional, has_var_kw = _handler_signature(m)
                handlers.setdefault(m.name[len("handle_"):], []).append(
                    _Handler(
                        op=m.name[len("handle_"):],
                        cls=node.name,
                        src=src,
                        node=m,
                        required=required,
                        optional=optional,
                        has_var_kw=has_var_kw,
                    )
                )
    return handlers


def _request_from_tuple(node: ast.AST) -> Optional[Tuple[str, Optional[Set[str]]]]:
    """(op, kwargs or None) from a literal request tuple, unwrapping a
    literal trace envelope; None when the shape is not the named-op plane."""
    if not isinstance(node, ast.Tuple):
        return None
    elts = node.elts
    if len(elts) == 3 and const_str(elts[0]) == OBS_FRAME_MARK:
        return _request_from_tuple(elts[2])
    if len(elts) != 2:
        return None  # actor protocol 4-tuples and friends: out of scope
    op = const_str(elts[0])
    if op is None:
        return None
    kw_node = elts[1]
    if isinstance(kw_node, ast.Dict):
        keys: Set[str] = set()
        for k in kw_node.keys:
            if k is None:  # **spread — arity unknowable
                return op, None
            ks = const_str(k)
            if ks is None:
                return op, None
            keys.add(ks)
        return op, keys
    return op, None


def _collect_call_sites(project: Project) -> List[_CallSite]:
    sites: List[_CallSite] = []
    for src in project:
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None:
                continue
            last = name.rsplit(".", 1)[-1]
            if last in ("rpc", "rpc_pooled") and len(node.args) >= 2:
                req = _request_from_tuple(node.args[1])
                if req is not None:
                    sites.append(_CallSite(req[0], src, node, req[1]))
            elif last == "head_rpc" and node.args:
                op = const_str(node.args[0])
                if op is None:
                    continue
                kwargs: Optional[Set[str]] = set()
                for kw in node.keywords:
                    if kw.arg is None:  # **spread
                        kwargs = None
                        break
                    if kw.arg != "timeout":  # consumed by the helper itself
                        kwargs.add(kw.arg)
                sites.append(_CallSite(op, src, node, kwargs))
    return sites


@dataclasses.dataclass
class _Method:
    cls: str
    required: List[str]
    optional: List[str]
    has_var_args: bool
    has_var_kw: bool

    def binds(self, n_pos: int, kwnames: Set[str]) -> bool:
        params = list(self.required) + list(self.optional)
        if not self.has_var_args and n_pos > len(params):
            return False
        positional = set(params[:n_pos])
        if not self.has_var_kw and not kwnames <= set(params) - positional:
            return False
        return set(self.required) <= positional | kwnames

    def signature(self) -> str:
        parts = list(self.required) + [f"{o}=…" for o in self.optional]
        if self.has_var_args:
            parts.append("*a")
        if self.has_var_kw:
            parts.append("**kw")
        return f"{self.cls}.({', '.join(parts)})"


def _collect_class_methods(project: Project) -> Dict[str, List[_Method]]:
    """Every method on every project class, by name — the actor-dispatch
    plane's op inventory (handles are untyped, so the inventory is
    project-wide; a name NO class defines is a typo'd dispatch)."""
    methods: Dict[str, List[_Method]] = {}
    for src in project:
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for m in node.body:
                if not isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                args = m.args
                names = [a.arg for a in args.args[1:]]  # drop self
                n_def = len(args.defaults)
                required = names[: len(names) - n_def] if n_def else list(names)
                optional = names[len(names) - n_def:] if n_def else []
                for a, d in zip(args.kwonlyargs, args.kw_defaults):
                    (optional if d is not None else required).append(a.arg)
                methods.setdefault(m.name, []).append(
                    _Method(
                        cls=node.name,
                        required=required,
                        optional=optional,
                        has_var_args=args.vararg is not None,
                        has_var_kw=args.kwarg is not None,
                    )
                )
    return methods


def _actor_dispatch_sites(project: Project):
    """(method_name, n_positional, kwnames_or_None, src, node) for every
    ``<expr>.<method>.remote(...)`` / ``<expr>.<method>.options(...).remote``
    call. The receiver may be arbitrary (subscripts, attributes); only the
    two trailing attribute hops name the op."""
    for src in project:
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "remote"
            ):
                continue
            inner = node.func.value
            if (
                isinstance(inner, ast.Call)
                and isinstance(inner.func, ast.Attribute)
                and inner.func.attr == "options"
            ):
                inner = inner.func.value
            if not isinstance(inner, ast.Attribute):
                continue  # e.g. a bare name called .remote on: not this plane
            kwnames: Optional[Set[str]] = set()
            for kw in node.keywords:
                if kw.arg is None:  # **spread — shape unknowable
                    kwnames = None
                    break
                kwnames.add(kw.arg)
            n_pos = len(node.args)
            if any(isinstance(a, ast.Starred) for a in node.args):
                n_pos = -1  # *spread: positional count unknowable
            yield inner.attr, n_pos, kwnames, src, node


class RpcProtocolRule:
    name = "rpc-protocol"

    def _check_actor_plane(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        methods = _collect_class_methods(project)
        if not methods:
            return findings
        for name, n_pos, kwnames, src, node in _actor_dispatch_sites(project):
            cands = methods.get(name)
            if not cands:
                findings.append(
                    src.finding(
                        self.name, node,
                        f"unknown actor method '{name}': no project class "
                        "defines it",
                    )
                )
                continue
            if len(cands) != 1 or n_pos < 0 or kwnames is None:
                continue  # ambiguous target or spread args: arity unknowable
            if not cands[0].binds(n_pos, kwnames):
                sent = ", ".join(
                    [f"<{n_pos} positional>"] + sorted(kwnames)
                )
                findings.append(
                    src.finding(
                        self.name, node,
                        f"actor arity mismatch for '{name}': call sends "
                        f"({sent}) but {name}{cands[0].signature()[len(cands[0].cls):]} "
                        f"on {cands[0].cls} cannot bind it",
                    )
                )
        return findings

    def check_project(self, project: Project) -> List[Finding]:
        findings: List[Finding] = self._check_actor_plane(project)
        handlers = _collect_handlers(project)
        sites = _collect_call_sites(project)
        if not handlers:
            # nothing serves the named-op plane in this scan (e.g. a fixture
            # subset) — call sites alone cannot be validated
            return findings
        called_ops: Set[str] = set()
        for site in sites:
            called_ops.add(site.op)
            cands = handlers.get(site.op)
            if not cands:
                findings.append(
                    site.src.finding(
                        self.name, site.node,
                        f"unknown op '{site.op}': no handle_{site.op} on any "
                        "protocol server",
                    )
                )
                continue
            if site.kwargs is not None and not any(
                h.binds(site.kwargs) for h in cands
            ):
                sigs = "; ".join(h.signature() for h in cands)
                sent = ", ".join(sorted(site.kwargs)) or "<none>"
                findings.append(
                    site.src.finding(
                        self.name, site.node,
                        f"arity mismatch for op '{site.op}': call sends "
                        f"({sent}) but no handler binds it — {sigs}",
                    )
                )
        for op, hs in sorted(handlers.items()):
            if op in called_ops:
                continue
            for h in hs:
                findings.append(
                    h.src.finding(
                        self.name, h.node,
                        f"dead handler {h.cls}.handle_{op}: no statically-"
                        "visible rpc/head_rpc call site sends this op",
                    )
                )
        return findings
