"""rpc-payload-safety: nothing process-bound crosses the wire.

Every RPC frame is cloudpickled (``send_frame``), so most objects "work" —
until a payload smuggles process-bound state: a lock pickles into a NEW
unlocked lock on the peer, a socket/file refuses to pickle at runtime, a
generator is consumed-once and unpicklable, and a raw jax device array drags
a device buffer through host sync + transfer on every send. All four are
invisible at the call site because pickling happens layers below.

The rule inspects, on the extracted RPC surface (:mod:`tools.analyze.rpc`):

- **call-site payloads** — literal frame-plane kwarg values, ``head_rpc``
  keyword values, and actor-plane ``.remote(...)`` arguments;
- **handler returns** — return expressions of frame handlers and of spawned
  classes' public (wire-reachable) methods, plus ``yield`` anywhere in a
  handler body (the return value would BE a generator).

Flagged payload shapes:

- generator expressions;
- ``threading`` primitives and ``Thread`` constructions;
- ``socket.socket(...)`` / ``create_connection(...)`` / bare ``open(...)``;
- known lock objects (``self.lock`` etc., resolved through the project lock
  model — the same identities lock-order/blocking-under-lock use);
- raw jax expressions (``jnp.*`` / ``jax.*``) outside the approved marshaling
  helpers (``np.asarray``/``np.array``/``jax.device_get``/``.tolist()``/
  ``.item()``/``float``/``int``/``list``/``bytes``/``to_numpy`` — anything
  that lands host-side before pickling).

Names are traced one assignment back within the enclosing function when the
assignment is unique; everything else is out of scope (under-reporting beats
false positives on a lint gate).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from tools.analyze.core import Finding, Project, SourceFile, call_name
from tools.analyze.locks import get_lock_model, module_of
from tools.analyze.rpc import own_nodes

_THREADING_CTORS = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "Event", "Barrier", "Thread",
}
_SOCKET_CTORS = {"socket", "create_connection"}
_JAX_PREFIXES = ("jnp.", "jax.")
#: call terminals that marshal a device value host-side before pickling
_APPROVED_MARSHALS = {
    "asarray", "array", "device_get", "tolist", "item", "float", "int",
    "list", "bytes", "to_numpy", "dumps",
}


def _classify(expr: ast.AST, env: Dict[str, ast.AST], depth: int = 0) -> Optional[str]:
    """Why this expression is wire-unsafe, or None. ``env`` maps local names
    to their unique assignment value (one provenance hop)."""
    if isinstance(expr, ast.GeneratorExp):
        return "a generator expression (consumed-once, unpicklable)"
    if isinstance(expr, ast.Call):
        name = call_name(expr)
        if name is None:
            return None
        terminal = name.rsplit(".", 1)[-1]
        if terminal in _APPROVED_MARSHALS:
            return None  # marshaled host-side: safe by construction
        if terminal in _THREADING_CTORS:
            return f"a threading primitive ({name}(...))"
        if terminal in _SOCKET_CTORS or name == "open":
            return f"an OS handle ({name}(...))"
        if name.startswith(_JAX_PREFIXES):
            return (
                f"a raw jax value ({name}(...)) — marshal host-side first "
                "(np.asarray / jax.device_get / .tolist())"
            )
        return None
    if isinstance(expr, ast.Name) and depth == 0:
        assigned = env.get(expr.id)
        if assigned is not None:
            why = _classify(assigned, env, depth=1)
            if why is not None:
                return f"'{expr.id}', assigned {why}"
    return None


def _local_env(fn: ast.AST) -> Dict[str, ast.AST]:
    """name -> value for locals assigned EXACTLY once in fn's own body."""
    counts: Dict[str, int] = {}
    values: Dict[str, ast.AST] = {}
    for node in own_nodes(fn):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    counts[target.id] = counts.get(target.id, 0) + 1
                    values[target.id] = node.value
    return {k: v for k, v in values.items() if counts.get(k) == 1}


def _enclosing_functions(src: SourceFile):
    """(funcdef, class_name) for every function, innermost last, so a payload
    node can be matched to its tightest enclosing scope."""
    out = []

    def walk(node: ast.AST, cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((child, cls))
                walk(child, cls)
            else:
                walk(child, cls)

    if src.tree is not None:
        walk(src.tree, None)
    return out


class RpcPayloadSafetyRule:
    """Process-bound state (locks, sockets, threads, generators, raw jax
    arrays) in RPC call-site payloads or handler returns."""

    name = "rpc-payload-safety"

    def check_project(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        surface = project.rpc_surface()
        model = get_lock_model(project)
        # call-site payloads, resolved in their enclosing function's scope
        env_cache: Dict[int, Dict[str, ast.AST]] = {}
        scopes: Dict[str, List] = {}
        for call in surface.calls:
            src = call.src
            if src.display_path not in scopes:
                scopes[src.display_path] = _enclosing_functions(src)
            fn, cls = _enclosing(scopes[src.display_path], call.node)
            env = {}
            if fn is not None:
                if id(fn) not in env_cache:
                    env_cache[id(fn)] = _local_env(fn)
                env = env_cache[id(fn)]
            module = module_of(src)
            for payload in call.payloads:
                why = _classify(payload, env)
                if why is None:
                    lock = model.resolve(payload, cls, module)
                    if lock is not None:
                        why = (
                            f"the lock '{lock}' (pickles into a NEW unlocked "
                            "lock on the peer)"
                        )
                if why is not None:
                    findings.append(
                        src.finding(
                            self.name, payload,
                            f"'{call.op}' payload ships {why} — not wire-safe",
                        )
                    )
        # handler returns (frame plane + spawned classes' public methods)
        seen: set = set()
        for handlers in list(surface.frame_handlers.values()) + list(
            surface.actor_handlers.values()
        ):
            for h in handlers:
                if id(h.node) in seen:
                    continue
                seen.add(id(h.node))
                if h.has_yield:
                    findings.append(
                        h.src.finding(
                            self.name, h.node,
                            f"handler {h.signature()} is a generator — its "
                            "'return value' cannot cross the wire",
                        )
                    )
                    continue
                env = _local_env(h.node)
                module = module_of(h.src)
                for node in own_nodes(h.node):
                    if not isinstance(node, ast.Return) or node.value is None:
                        continue
                    why = _classify(node.value, env)
                    if why is None:
                        lock = model.resolve(node.value, h.cls, module)
                        if lock is not None:
                            why = f"the lock '{lock}'"
                    if why is not None:
                        findings.append(
                            h.src.finding(
                                self.name, node,
                                f"handler {h.signature()} returns {why} — "
                                "not wire-safe",
                            )
                        )
        return findings


def _enclosing(scopes, node: ast.AST):
    """The innermost (funcdef, class_name) whose span contains node."""
    line = getattr(node, "lineno", None)
    if line is None:
        return None, None
    best = (None, None)
    best_span = None
    for fn, cls in scopes:
        end = getattr(fn, "end_lineno", fn.lineno)
        if fn.lineno <= line <= end:
            span = end - fn.lineno
            if best_span is None or span <= best_span:
                best, best_span = (fn, cls), span
    return best
