"""guarded-by: annotated attributes are only touched under their lock.

The ``_reap_after_kill`` double-read bug class (ADVICE r5): shared mutable
state read twice outside the lock races a concurrent writer. Eraser-style
lockset checking, scoped to what Python's dynamism allows: the *author*
declares the locking discipline with a comment and the checker enforces the
lexical part of it.

Annotation forms (trailing comments)::

    self.actors = {}          # guarded-by: self.lock
    _lib = None               # guarded-by: _lib_lock           (module global)
    def _on_actor_death(...): # guarded-by: self.lock held      (lock held by caller)

- An attribute annotated in a class body is checked across every method of
  that class: each ``self.<attr>`` load/store must sit lexically inside
  ``with <lock>`` (alternate lock names: ``lockA|lockB`` — e.g. a Condition
  constructed over the same lock).
- ``__init__`` is exempt (no concurrent access before construction returns).
- A method annotated ``... held`` asserts its callers hold the lock; its body
  is treated as locked (the claim itself is the reviewable artifact).
- Nested functions/lambdas reset the lock context — a closure runs later,
  possibly on another thread — unless their ``def`` carries ``held``.
- Module-level globals: every Name load/store inside any function must be
  under ``with <lock>``; module top-level (import-time, single-threaded) is
  exempt.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from typing import Dict, List, Optional, Set, Tuple

from tools.analyze.core import Finding, Project, SourceFile, dotted_name

_ANNOT_RE = re.compile(
    r"guarded-by:\s*(?P<lock>[A-Za-z0-9_.|]+)\s*(?P<held>held)?"
)


def _annotations(src: SourceFile) -> Dict[int, Tuple[str, bool]]:
    """line -> (lock spec, is_held_marker) for every guarded-by comment."""
    out: Dict[int, Tuple[str, bool]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(src.text).readline)
        comments = [
            (t.start[0], t.string) for t in tokens if t.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, SyntaxError):
        comments = [
            (i + 1, line) for i, line in enumerate(src.lines) if "#" in line
        ]
    for lineno, comment in comments:
        m = _ANNOT_RE.search(comment)
        if m:
            out[lineno] = (m.group("lock"), m.group("held") is not None)
    return out


def _assign_target_names(stmt: ast.stmt) -> List[ast.AST]:
    if isinstance(stmt, ast.Assign):
        return list(stmt.targets)
    if isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        return [stmt.target]
    return []


class _LockWalker(ast.NodeVisitor):
    """Walk one function body tracking whether a matching lock is held."""

    def __init__(
        self,
        rule: "GuardedByRule",
        src: SourceFile,
        findings: List[Finding],
        attrs: Dict[str, str],
        globals_: Dict[str, str],
        annotations: Dict[int, Tuple[str, bool]],
        locked: bool,
        lock_names: Set[str],
    ):
        self.rule = rule
        self.src = src
        self.findings = findings
        self.attrs = attrs  # guarded self-attr -> lock spec
        self.globals = globals_  # guarded module global -> lock spec
        self.annotations = annotations
        self.locked = locked
        self.lock_names = lock_names  # lock specs currently held

    def _spec_names(self, spec: str) -> Set[str]:
        return {s.strip() for s in spec.split("|") if s.strip()}

    def _holds(self, spec: str) -> bool:
        return self.locked and bool(self._spec_names(spec) & self.lock_names)

    def visit_With(self, node: ast.With) -> None:
        acquired: Set[str] = set()
        for item in node.items:
            name = dotted_name(item.context_expr)
            if name is not None:
                acquired.add(name)
        for item in node.items:
            self.visit(item.context_expr)
        prev_locked, prev_names = self.locked, set(self.lock_names)
        if acquired:
            self.locked = True
            self.lock_names |= acquired
        for stmt in node.body:
            self.visit(stmt)
        self.locked, self.lock_names = prev_locked, prev_names

    visit_AsyncWith = visit_With

    def _enter_nested(self, node) -> None:
        annot = self.annotations.get(node.lineno)
        held = annot is not None and annot[1]
        inner = _LockWalker(
            self.rule, self.src, self.findings, self.attrs, self.globals,
            self.annotations,
            locked=held,
            lock_names=self._spec_names(annot[0]) if held else set(),
        )
        for stmt in node.body:
            inner.visit(stmt)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter_nested(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        inner = _LockWalker(
            self.rule, self.src, self.findings, self.attrs, self.globals,
            self.annotations, locked=False, lock_names=set(),
        )
        inner.visit(node.body)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (
            isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr in self.attrs
        ):
            spec = self.attrs[node.attr]
            if not self._holds(spec):
                self.findings.append(
                    self.src.finding(
                        self.rule.name, node,
                        f"'self.{node.attr}' is guarded by '{spec}' but "
                        f"accessed outside 'with {spec}'",
                    )
                )
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if node.id in self.globals:
            spec = self.globals[node.id]
            if not self._holds(spec):
                self.findings.append(
                    self.src.finding(
                        self.rule.name, node,
                        f"global '{node.id}' is guarded by '{spec}' but "
                        f"accessed outside 'with {spec}'",
                    )
                )
        self.generic_visit(node)


class GuardedByRule:
    name = "guarded-by"

    def check_project(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in project:
            if src.tree is None:
                continue
            annotations = _annotations(src)
            if not annotations:
                continue
            self._check_file(src, annotations, findings)
        return findings

    def _check_file(
        self,
        src: SourceFile,
        annotations: Dict[int, Tuple[str, bool]],
        findings: List[Finding],
    ) -> None:
        tree = src.tree
        # module-level guarded globals: annotated top-level assignments
        guarded_globals: Dict[str, str] = {}
        for stmt in tree.body:
            annot = annotations.get(stmt.lineno)
            if annot is None or annot[1]:
                continue
            for target in _assign_target_names(stmt):
                if isinstance(target, ast.Name):
                    guarded_globals[target.id] = annot[0]
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                self._check_class(src, node, annotations, guarded_globals, findings)
        if guarded_globals:
            # functions outside any class still must respect guarded globals
            # (class methods are covered by _check_class, which walks them
            # whenever guarded attrs OR guarded globals exist)
            for stmt in tree.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    annot = annotations.get(stmt.lineno)
                    held = annot is not None and annot[1]
                    walker = _LockWalker(
                        self, src, findings, {}, guarded_globals, annotations,
                        locked=held,
                        lock_names=(
                            {s for s in annot[0].split("|") if s} if held else set()
                        ),
                    )
                    for sub in stmt.body:
                        walker.visit(sub)

    def _check_class(
        self,
        src: SourceFile,
        cls: ast.ClassDef,
        annotations: Dict[int, Tuple[str, bool]],
        guarded_globals: Dict[str, str],
        findings: List[Finding],
    ) -> None:
        guarded_attrs: Dict[str, str] = {}
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for stmt in ast.walk(method):
                if not isinstance(stmt, ast.stmt):
                    continue
                annot = annotations.get(stmt.lineno)
                if annot is None or annot[1]:
                    continue
                for target in _assign_target_names(stmt):
                    if (
                        isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"
                    ):
                        guarded_attrs[target.attr] = annot[0]
        if not guarded_attrs and not guarded_globals:
            return
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if method.name == "__init__":
                continue
            annot = annotations.get(method.lineno)
            held = annot is not None and annot[1]
            walker = _LockWalker(
                self, src, findings, guarded_attrs, guarded_globals,
                annotations,
                locked=held,
                lock_names=(
                    {s.strip() for s in annot[0].split("|") if s.strip()}
                    if held
                    else set()
                ),
            )
            for stmt in method.body:
                walker.visit(stmt)
