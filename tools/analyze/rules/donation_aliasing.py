"""donation-aliasing: externally-owned host memory must not reach donated jits.

The PR 2 streaming-NaN use-after-free, as a checkable property: on CPU jax,
``jax.device_put`` / ``jnp.asarray`` zero-copy suitably-aligned numpy arrays,
so an array staged from externally-owned host memory (an orbax restore
result, an Arrow ``to_numpy`` view, ``np.frombuffer``/``memmap``) ALIASES
that memory — and handing it to a jit built with ``donate_argnums`` lets XLA
reuse the buffer while its true owner still holds it. The fix is an owned
copy **in the target sharding**: ``jnp.array(..., copy=True)`` (a host-side
``np.copy`` does NOT help — the copy is zero-copy-staged and donated all the
same, which is why plain ``np.copy``/``.copy()`` do not sanitize here).

Heuristic intraprocedural dataflow with light cross-function propagation
(module-local, call-by-name — covers the builder/runner split in
``jax_estimator``):

- **origins** (taint): ``*._restore_checkpoint(...)``, ``*.restore(...)``,
  ``np.frombuffer/memmap/load``, ``*.to_numpy(...)``.
- **propagators** (keep taint): ``device_put``, ``jnp.asarray``,
  ``device_put_stacked``/``device_put_batch``, subscripts, tuples, ternaries,
  and ``jax.tree.map``/``fmap`` whose mapping fn is not itself sanitizing.
- **sanitizers** (clear taint): ``jnp.array(x)`` / ``jnp.array(x, copy=True)``
  (device-side owned copy; ``copy=False`` keeps taint), including through a
  local helper or lambda whose returned expression sanitizes.
- **sinks**: calls to names bound from ``jax.jit(..., donate_argnums=D)``,
  ``partial_jit(donate_argnums=D)(fn)`` or ``checked_jit(fn, donate_argnums=D)``
  with non-empty ``D``; when ``D`` isn't a literal (e.g. ``donate`` resolved
  through a conditional) every positional argument is treated as donated.

This is a linter, not an alias analysis: unknown calls are assumed to return
owned values (under-reporting beats drowning the signal), and data that
crosses module boundaries through containers is not tracked. The runtime half
of the defence — ``RAYDP_TPU_SANITIZE=donation`` (raydp_tpu/sanitize.py) —
catches what escapes the static net.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.analyze.core import Finding, Project, SourceFile, call_name, const_str

_ORIGIN_LAST = {"_restore_checkpoint", "restore", "to_numpy"}
_ORIGIN_FULL = {
    "np.frombuffer", "numpy.frombuffer",
    "np.memmap", "numpy.memmap",
    "np.load", "numpy.load",
}
_PROPAGATE_LAST = {
    "device_put", "asarray", "ascontiguousarray",
    "device_put_stacked", "device_put_batch",
    "make_array_from_process_local_data",
    "reshape", "ravel", "squeeze", "astype", "view",
}
_TREEMAP_LAST = {"map", "tree_map", "fmap", "_fmap"}
_JIT_LAST = {"jit", "checked_jit"}
_JIT_FACTORY_LAST = {"partial_jit", "checked_partial_jit"}


def _is_jnp_array_name(name: str) -> bool:
    return name in ("jnp.array", "jax.numpy.array")


def _literal_positions(node: Optional[ast.AST]) -> Optional[Tuple[bool, Set[int]]]:
    """(donating, positions) from a donate_argnums expression; None when the
    expression cannot be resolved statically."""
    if node is None:
        return (False, set())
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (True, {node.value})
    if isinstance(node, (ast.Tuple, ast.List)):
        positions: Set[int] = set()
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                positions.add(elt.value)
            else:
                return None
        return (bool(positions), positions)
    if isinstance(node, ast.IfExp):
        a = _literal_positions(node.body)
        b = _literal_positions(node.orelse)
        if a is None or b is None:
            return None
        return (a[0] or b[0], a[1] | b[1])
    return None


class _FunctionInfo:
    def __init__(self, node):
        self.node = node
        self.param_names = [a.arg for a in node.args.args]
        self.param_taints: Dict[str, str] = {}  # param -> origin description


class _ModuleAnalysis:
    def __init__(self, rule: "DonationAliasingRule", src: SourceFile):
        self.rule = rule
        self.src = src
        self.functions: Dict[str, _FunctionInfo] = {}
        self.sanitizing_fns: Set[str] = set()
        # donated-callable name -> donated positions (None = unknown/all)
        self.donated: Dict[str, Optional[Set[int]]] = {}
        self.findings: Dict[Tuple[int, int, str], Finding] = {}

    # -- phase A: tables ----------------------------------------------------

    def collect(self) -> None:
        for node in ast.walk(self.src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = _FunctionInfo(node)
        for name, info in self.functions.items():
            if self._fn_sanitizes(info.node):
                self.sanitizing_fns.add(name)
        # donated jit assignments anywhere in the module, with a per-scope
        # pass so `donate = (0, 1) if flag else ()` resolves through the name
        scopes: List[Sequence[ast.stmt]] = [self.src.tree.body]
        scopes += [info.node.body for info in self.functions.values()]
        for body in scopes:
            literal_env: Dict[str, Tuple[bool, Set[int]]] = {}
            for stmt in self._flat_statements(body):
                if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                    continue
                target = stmt.targets[0]
                if not isinstance(target, ast.Name):
                    continue
                lit = _literal_positions(stmt.value)
                if lit is not None:
                    literal_env[target.id] = lit
                donated = self._donated_positions(stmt.value, literal_env)
                if donated is not None:
                    donating, positions = donated
                    if donating:
                        self.donated[target.id] = positions

    def _flat_statements(self, body: Sequence[ast.stmt]) -> List[ast.stmt]:
        out: List[ast.stmt] = []
        stack = list(body)
        while stack:
            stmt = stack.pop(0)
            out.append(stmt)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub and not isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    stack.extend(sub)
            for handler in getattr(stmt, "handlers", ()):
                stack.extend(handler.body)
        return out

    def _donate_kw(self, call: ast.Call) -> Optional[ast.AST]:
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                return kw.value
        return None

    def _donated_positions(
        self, value: ast.AST, literal_env: Dict[str, Tuple[bool, Set[int]]]
    ) -> Optional[Tuple[bool, Optional[Set[int]]]]:
        """(donating, positions) for a jit-building RHS, else None."""
        if not isinstance(value, ast.Call):
            return None
        name = call_name(value)
        call = None
        if name is not None and name.rsplit(".", 1)[-1] in _JIT_LAST:
            call = value
        elif isinstance(value.func, ast.Call):
            inner_name = call_name(value.func)
            if (
                inner_name is not None
                and inner_name.rsplit(".", 1)[-1] in _JIT_FACTORY_LAST
            ):
                call = value.func
        if call is None:
            return None
        donate = self._donate_kw(call)
        if donate is None:
            return (False, set())
        if isinstance(donate, ast.Name) and donate.id in literal_env:
            donating, positions = literal_env[donate.id]
            return (donating, positions)
        lit = _literal_positions(donate)
        if lit is not None:
            return (lit[0], lit[1])
        return (True, None)  # unresolvable expression: assume donating, all args

    def _fn_sanitizes(self, node) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Return) and sub.value is not None:
                if self._expr_sanitizes(sub.value):
                    return True
        return False

    def _expr_sanitizes(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name is not None and _is_jnp_array_name(name):
                for kw in node.keywords:
                    if kw.arg == "copy" and (
                        isinstance(kw.value, ast.Constant)
                        and kw.value.value is False
                    ):
                        return False
                return True
            if name is not None and name in self.sanitizing_fns:
                return True
            if name is not None and name.startswith("self."):
                return name[len("self."):] in self.sanitizing_fns
        return False

    def _mapper_sanitizes(self, fn_node: ast.AST) -> bool:
        if isinstance(fn_node, ast.Lambda):
            return self._expr_sanitizes(fn_node.body)
        name = None
        if isinstance(fn_node, (ast.Name, ast.Attribute)):
            from tools.analyze.core import dotted_name

            name = dotted_name(fn_node)
        if name is None:
            return False
        bare = name.rsplit(".", 1)[-1]
        return bare in self.sanitizing_fns or _is_jnp_array_name(name)

    # -- phase B: worklist taint analysis -----------------------------------

    def analyze(self) -> List[Finding]:
        self.collect()
        if not self.donated:
            return []
        worklist: List[Optional[str]] = [None]  # None = module body
        worklist += list(self.functions)
        seen_rounds = 0
        while worklist and seen_rounds < 4 * (len(self.functions) + 1):
            name = worklist.pop(0)
            seen_rounds += 1
            grew = self._analyze_scope(name)
            for changed in grew:
                if changed not in worklist:
                    worklist.append(changed)
        return list(self.findings.values())

    def _analyze_scope(self, name: Optional[str]) -> Set[str]:
        if name is None:
            body: Sequence[ast.stmt] = self.src.tree.body
            env: Dict[str, str] = {}
        else:
            info = self.functions[name]
            body = info.node.body
            env = dict(info.param_taints)
        grew: Set[str] = set()
        for stmt in self._flat_statements(body):
            if isinstance(stmt, ast.Assign):
                t = self._taint(stmt.value, env)
                for target in stmt.targets:
                    self._assign(target, stmt.value, t, env)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                t = self._taint(stmt.value, env)
                self._assign(stmt.target, stmt.value, t, env)
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    self._check_call(sub, env, grew)
        return grew

    def _assign(
        self, target: ast.AST, value: ast.AST, t: Optional[str],
        env: Dict[str, str],
    ) -> None:
        if isinstance(target, ast.Name):
            if t is None:
                env.pop(target.id, None)
            else:
                env[target.id] = t
        elif isinstance(target, (ast.Tuple, ast.List)):
            if isinstance(value, (ast.Tuple, ast.List)) and len(
                value.elts
            ) == len(target.elts):
                for tgt, val in zip(target.elts, value.elts):
                    self._assign(tgt, val, self._taint(val, env), env)
            else:
                for tgt in target.elts:
                    self._assign(tgt, value, t, env)

    def _taint(self, node: ast.AST, env: Dict[str, str]) -> Optional[str]:
        if isinstance(node, ast.Name):
            return env.get(node.id)
        if isinstance(node, ast.Starred):
            return self._taint(node.value, env)
        if isinstance(node, ast.Subscript):
            return self._taint(node.value, env)
        if isinstance(node, ast.IfExp):
            return self._taint(node.body, env) or self._taint(node.orelse, env)
        if isinstance(node, (ast.Tuple, ast.List)):
            for elt in node.elts:
                t = self._taint(elt, env)
                if t is not None:
                    return t
            return None
        if isinstance(node, ast.Call):
            return self._call_taint(node, env)
        if isinstance(node, ast.Await):
            return self._taint(node.value, env)
        return None

    def _call_taint(self, node: ast.Call, env: Dict[str, str]) -> Optional[str]:
        name = call_name(node)
        if name is None:
            return None
        if self._expr_sanitizes(node):
            return None
        last = name.rsplit(".", 1)[-1]
        if name in _ORIGIN_FULL or last in _ORIGIN_LAST:
            return f"{name}(...) at line {node.lineno}"
        if last in _TREEMAP_LAST and len(node.args) >= 2:
            if self._mapper_sanitizes(node.args[0]):
                return None
            for arg in node.args[1:]:
                t = self._taint(arg, env)
                if t is not None:
                    return t
            return None
        if last in _PROPAGATE_LAST:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                t = self._taint(arg, env)
                if t is not None:
                    return t
            return None
        return None

    def _check_call(
        self, node: ast.Call, env: Dict[str, str], grew: Set[str]
    ) -> None:
        name = call_name(node)
        if name is None:
            return
        bare = name[len("self."):] if name.startswith("self.") else name
        if "." in bare:
            return
        if bare in self.donated:
            positions = self.donated[bare]
            for i, arg in enumerate(node.args):
                if positions is not None and i not in positions:
                    continue
                t = self._taint(arg, env)
                if t is not None:
                    donated = (
                        "all args (donate_argnums not statically resolvable)"
                        if positions is None
                        else f"donate_argnums={sorted(positions)}"
                    )
                    f = self.src.finding(
                        self.rule.name, node,
                        f"argument {i} of donated jit '{bare}' ({donated}) "
                        f"is staged from externally-owned host memory "
                        f"(origin: {t}) without an owned copy — use "
                        "jnp.array(..., copy=True) in the target sharding",
                    )
                    self.findings.setdefault((f.line, f.col, f.message), f)
        if bare in self.functions:
            info = self.functions[bare]
            # a self.method(...) call binds positionals starting at param 1
            offset = (
                1
                if name.startswith("self.") and info.param_names[:1] == ["self"]
                else 0
            )
            for i, arg in enumerate(node.args):
                t = self._taint(arg, env)
                if t is not None and i + offset < len(info.param_names):
                    param = info.param_names[i + offset]
                    if param not in info.param_taints:
                        info.param_taints[param] = t
                        grew.add(bare)


class DonationAliasingRule:
    name = "donation-aliasing"

    def check_project(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in project:
            if src.tree is None:
                continue
            # cheap gate: only modules that mention donation at all
            if "donate_argnums" not in src.text:
                continue
            findings.extend(_ModuleAnalysis(self, src).analyze())
        return findings
