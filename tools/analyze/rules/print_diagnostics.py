"""print-diagnostics: no bare ``print()`` / ``traceback.print_exc()``.

Crash output from runtime processes must go through the structured logger
(``raydp_tpu.obs.log``) so every line carries the wall timestamp, process
role, and actor id — diagnostics interleaved from dozens of processes in the
session dir are otherwise unattributable. Replaces (and widens to the whole
package) the grep lint that previously covered only ``cluster/`` in CI.

The logger implementation itself is exempt; deliberate console output (e.g.
``DataFrame.show()``) carries a line suppression.
"""

from __future__ import annotations

import ast
from typing import List

from tools.analyze.core import Finding, Project, call_name

_ALLOWED_SUFFIXES = ("obs/logging.py",)


class PrintDiagnosticsRule:
    name = "print-diagnostics"

    def check_project(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in project:
            if src.tree is None:
                continue
            path = src.display_path.replace("\\", "/")
            if path.endswith(_ALLOWED_SUFFIXES):
                continue
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name is None:
                    continue
                last = name.rsplit(".", 1)[-1]
                if last == "print" or name == "print":
                    findings.append(
                        src.finding(
                            self.name, node,
                            "bare print() — use raydp_tpu.obs.log so the "
                            "line carries role + actor id",
                        )
                    )
                elif last == "print_exc":
                    findings.append(
                        src.finding(
                            self.name, node,
                            "traceback.print_exc() — use "
                            "raydp_tpu.obs.log.exception(...) instead",
                        )
                    )
        return findings
