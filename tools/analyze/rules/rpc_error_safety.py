"""rpc-error-safety: exceptions crossing an RPC boundary must survive it.

Head/worker/replica RPC ops ship exceptions to the caller as pickled
``("err", exc)`` payloads. Two ways that breaks:

- the type is defined in a module the *client* process never imports (an
  etl/serve-internal class) — unpickling raises ``ModuleNotFoundError``
  inside the error path, replacing the real failure. Every exception raised
  inside an RPC-served file must be stdlib or defined in
  ``cluster/common.py`` (imported by every process at bootstrap).
- the type's ``__init__`` takes required extra args it does not forward to
  ``super().__init__``: ``BaseException.__reduce__`` replays ``self.args``,
  so round-trip loses the attrs (the ``TenantQuotaError.tenant`` contract).

RPC-served files are the known serving modules below; a fixture or new
surface opts in with a ``# raydp-lint: rpc-surface`` marker comment. Types
imported from outside the project are opaque (not flagged). Bare ``raise``
re-raises are fine — they propagate whatever arrived.
"""

from __future__ import annotations

import ast
import builtins
from typing import Dict, List, Optional, Set

from tools.analyze.core import Finding, Project

_COMMON = "cluster/common.py"

_RPC_SURFACE_FILES = {
    "raydp_tpu/cluster/head.py",
    "raydp_tpu/cluster/worker.py",
    "raydp_tpu/cluster/agent.py",
    "raydp_tpu/store/block_service.py",
    "raydp_tpu/etl/executor.py",
    "raydp_tpu/serve/replica.py",
}

_MARKER = "raydp-lint: rpc-surface"


def _is_builtin_exception(name: str) -> bool:
    obj = getattr(builtins, name, None)
    return isinstance(obj, type) and issubclass(obj, BaseException)


def _exc_classes(tree: ast.AST) -> Dict[str, ast.ClassDef]:
    """Class defs in this module that look like exception types: a base is a
    builtin exception or an *Error/*Exception-named class."""
    out: Dict[str, ast.ClassDef] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for base in node.bases:
            name = base.attr if isinstance(base, ast.Attribute) else (
                base.id if isinstance(base, ast.Name) else None
            )
            if name and (
                _is_builtin_exception(name)
                or name.endswith(("Error", "Exception"))
            ):
                out[node.name] = node
                break
    return out


def _raised_type_name(node: ast.Raise) -> Optional[str]:
    exc = node.exc
    if exc is None:
        return None  # bare re-raise
    if isinstance(exc, ast.Call):
        exc = exc.func
    if isinstance(exc, ast.Name):
        return exc.id
    if isinstance(exc, ast.Attribute):
        return exc.attr  # P.ProgramCacheMiss -> ProgramCacheMiss
    return None


def _init_forwards_args(cls: ast.ClassDef) -> Optional[List[str]]:
    """None if the class has no custom ``__init__`` (or defines
    ``__reduce__``); otherwise the list of required extra params NOT
    forwarded positionally to ``super().__init__``."""
    init = None
    for item in cls.body:
        if isinstance(item, ast.FunctionDef):
            if item.name == "__reduce__":
                return None
            if item.name == "__init__":
                init = item
    if init is None:
        return None
    params = [a.arg for a in init.args.args[1:]]  # drop self
    n_defaults = len(init.args.defaults)
    required = params[: len(params) - n_defaults] if n_defaults else params
    if not required:
        return None
    forwarded: Set[str] = set()
    for node in ast.walk(init):
        if isinstance(node, ast.Call):
            fn = node.func
            is_super_init = (
                isinstance(fn, ast.Attribute)
                and fn.attr == "__init__"
                and isinstance(fn.value, ast.Call)
                and isinstance(fn.value.func, ast.Name)
                and fn.value.func.id == "super"
            )
            if is_super_init:
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        forwarded.add(arg.id)
                    elif isinstance(arg, ast.Starred) and isinstance(
                        arg.value, ast.Name
                    ):
                        forwarded.add(arg.value.id)
                    elif isinstance(arg, ast.JoinedStr):
                        for part in ast.walk(arg):
                            if isinstance(part, ast.Name):
                                forwarded.add(part.id)
    missing = [p for p in required if p not in forwarded]
    return missing or None


class RpcErrorSafetyRule:
    name = "rpc-error-safety"

    def check_project(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []

        # name -> defining display path, for every exception-ish class
        defined_in: Dict[str, str] = {}
        common_classes: Dict[str, ast.ClassDef] = {}
        for src in project:
            if src.tree is None:
                continue
            classes = _exc_classes(src.tree)
            path = src.display_path.replace("\\", "/")
            for cname, cnode in classes.items():
                defined_in.setdefault(cname, src.display_path)
                if path.endswith(_COMMON):
                    common_classes[cname] = cnode

        # ---- pickle round-trip contract on cluster/common.py types
        for src in project:
            path = src.display_path.replace("\\", "/")
            if not path.endswith(_COMMON) or src.tree is None:
                continue
            for cname, cnode in _exc_classes(src.tree).items():
                missing = _init_forwards_args(cnode)
                if missing:
                    findings.append(
                        src.finding(
                            self.name, cnode,
                            f"exception `{cname}` takes required arg(s) "
                            f"{', '.join(missing)} but does not forward them "
                            "to super().__init__ — BaseException.__reduce__ "
                            "replays self.args, so pickling across the RPC "
                            "boundary loses them; forward the args or define "
                            "__reduce__",
                        )
                    )

        # ---- raises inside RPC-served files
        for src in project:
            if src.tree is None:
                continue
            path = src.display_path.replace("\\", "/")
            is_surface = path in _RPC_SURFACE_FILES or _MARKER in src.text
            if not is_surface:
                continue
            local_classes = set(_exc_classes(src.tree))
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Raise):
                    continue
                tname = _raised_type_name(node)
                if tname is None or _is_builtin_exception(tname):
                    continue
                home = defined_in.get(tname)
                if home is None:
                    continue  # imported from outside the project: opaque
                home_norm = home.replace("\\", "/")
                if home_norm.endswith(_COMMON):
                    continue
                if tname in local_classes and path.endswith(_COMMON):
                    continue
                findings.append(
                    src.finding(
                        self.name, node,
                        f"raises `{tname}` (defined in {home}) inside an "
                        "RPC-served op — the client process may not import "
                        "that module, so unpickling the error payload fails; "
                        "define the type in cluster/common.py",
                    )
                )
        return findings
