"""except-order: handler-chain flow checks over the exception hierarchy.

PR 18's postmortem: ``service_block_fetch`` handled ``OSError`` (release the
pooled socket) but a later ``FileNotFoundError`` miss path returned early
without the release — ``FileNotFoundError ⊂ OSError`` and each miss poisoned
one pooled connection. Three structural checks:

- **shadowed-handler** — ``except B`` before ``except A`` where ``A ⊆ B``:
  the second handler is unreachable (a bare/``Exception`` handler earlier in
  the chain shadows every later one).
- **redundant-tuple-member** — ``except (A, B)`` where ``A ⊆ B``: the
  narrower member is dead weight and usually betrays a wrong mental model of
  the hierarchy (``socket.timeout`` *is* ``TimeoutError`` *is* ``OSError``).
- **divergent-cleanup** — sibling handlers where the narrow one
  (``FileNotFoundError``) reaches a resource-bearing try body but skips a
  cleanup call (``close``/``release``/``discard``/...) that the broad
  sibling (``OSError``) performs on a name the try body uses. The narrow
  handler intercepts a subset of the broad one's exceptions, so the cleanup
  silently stops happening for exactly those cases.

Types are resolved through builtins, the stdlib alias table
(``socket.timeout`` -> ``TimeoutError``, ``socket.error``/``IOError`` ->
``OSError``), and project-defined exception classes (base chains walked to a
builtin). Unresolvable types are opaque: never flagged.
"""

from __future__ import annotations

import ast
import builtins
from typing import Dict, List, Optional, Set, Tuple

from tools.analyze.core import Finding, Project

# stdlib names that are aliases of (or subclasses folded into) builtins
_ALIASES = {
    "timeout": "TimeoutError",     # socket.timeout
    "error": "OSError",            # socket.error
    "gaierror": "OSError",
    "herror": "OSError",
    "IOError": "OSError",
    "EnvironmentError": "OSError",
    "WindowsError": "OSError",
}

_CLEANUP_METHODS = {
    "close", "release", "discard", "unlink", "remove", "shutdown",
    "terminate", "kill", "cleanup", "rollback", "abort",
}


def _type_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class _Hierarchy:
    """Subclass queries over builtins + project exception classes."""

    def __init__(self, project: Project):
        self.bases: Dict[str, List[str]] = {}
        for src in project:
            if src.tree is None:
                continue
            for node in ast.walk(src.tree):
                if isinstance(node, ast.ClassDef):
                    names = [
                        n for n in (_type_name(b) for b in node.bases) if n
                    ]
                    if names:
                        self.bases.setdefault(node.name, names)

    def _builtin(self, name: str) -> Optional[type]:
        name = _ALIASES.get(name, name)
        obj = getattr(builtins, name, None)
        if isinstance(obj, type) and issubclass(obj, BaseException):
            return obj
        return None

    def _ancestors(self, name: str, seen: Optional[Set[str]] = None) -> Set[str]:
        seen = seen if seen is not None else set()
        if name in seen:
            return seen
        seen.add(name)
        for base in self.bases.get(name, ()):
            self._ancestors(base, seen)
        return seen

    def is_known(self, name: str) -> bool:
        return self._builtin(name) is not None or name in self.bases

    def is_subtype(self, a: str, b: str) -> bool:
        """Conservative: True only when provably a ⊆ b."""
        if a == b and self.is_known(a):
            return True
        bb = self._builtin(b)
        ab = self._builtin(a)
        if ab is not None and bb is not None:
            return issubclass(ab, bb)
        if a in self.bases:
            anc = self._ancestors(a)
            if b in anc:
                return True
            if bb is not None:
                for ancestor in anc:
                    anb = self._builtin(ancestor)
                    if anb is not None and issubclass(anb, bb):
                        return True
        return False


def _handler_types(handler: ast.ExceptHandler) -> List[Tuple[str, ast.AST]]:
    """(name, node) per caught type; [("<bare>", handler)] for ``except:``."""
    if handler.type is None:
        return [("<bare>", handler)]
    if isinstance(handler.type, ast.Tuple):
        out = []
        for elt in handler.type.elts:
            n = _type_name(elt)
            if n:
                out.append((n, elt))
        return out
    n = _type_name(handler.type)
    return [(n, handler.type)] if n else []


def _names_used(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _cleanup_receivers(body: List[ast.stmt]) -> Set[str]:
    """Root names whose attributes get cleanup calls (``sock.close()``,
    ``self._pool.discard(sock)`` -> {sock, self})."""
    out: Set[str] = set()
    for stmt in body:
        for node in ast.walk(stmt):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _CLEANUP_METHODS
            ):
                root = node.func.value
                while isinstance(root, ast.Attribute):
                    root = root.value
                if isinstance(root, ast.Name):
                    out.add(root.id)
                # args to pool.discard(sock) also name the resource
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        out.add(arg.id)
    return out


class ExceptOrderRule:
    name = "except-order"

    def check_project(self, project: Project) -> List[Finding]:
        hier = _Hierarchy(project)
        findings: List[Finding] = []
        for src in project:
            if src.tree is None:
                continue
            for node in ast.walk(src.tree):
                if isinstance(node, (ast.Try, getattr(ast, "TryStar", ast.Try))):
                    self._check_try(src, node, hier, findings)
        return findings

    def _check_try(self, src, node, hier: _Hierarchy, findings: List[Finding]):
        handlers = getattr(node, "handlers", [])
        if not handlers:
            return

        # ---- redundant tuple members
        for handler in handlers:
            if not isinstance(handler.type, ast.Tuple):
                continue
            types = _handler_types(handler)
            for i, (a, a_node) in enumerate(types):
                for j, (b, _) in enumerate(types):
                    if i == j:
                        continue
                    if a == b:
                        redundant = i > j  # duplicate: flag the later copy
                    else:
                        redundant = hier.is_subtype(a, b)
                    if redundant:
                        findings.append(
                            src.finding(
                                self.name, a_node,
                                f"`{a}` is redundant in this tuple — it is "
                                f"already caught as `{b}`"
                                + (
                                    ""
                                    if a == b
                                    else f" ({a} ⊆ {b})"
                                ),
                            )
                        )
                        break

        # ---- shadowed handlers across the chain
        prior: List[Tuple[str, ast.ExceptHandler]] = []
        for handler in handlers:
            types = _handler_types(handler)
            for tname, tnode in types:
                if tname == "<bare>":
                    continue
                for (pname, _ph) in prior:
                    if pname == "<bare>" or hier.is_subtype(tname, pname):
                        findings.append(
                            src.finding(
                                self.name, tnode,
                                f"handler for `{tname}` is unreachable — an "
                                "earlier handler already catches "
                                + (
                                    "everything (bare except)"
                                    if pname == "<bare>"
                                    else f"`{pname}` ({tname} ⊆ {pname})"
                                ),
                            )
                        )
                        break
                else:
                    continue
                break
            prior.extend((t, handler) for t, _ in types)

        # ---- divergent cleanup between overlapping siblings
        try_resources = _names_used_in_body(node.body)
        for i, narrow in enumerate(handlers):
            for broad in handlers[i + 1:]:
                self._check_divergent(
                    src, narrow, broad, hier, try_resources, findings
                )

    def _check_divergent(
        self, src, narrow, broad, hier, try_resources, findings
    ):
        narrow_types = [t for t, _ in _handler_types(narrow)]
        broad_types = [t for t, _ in _handler_types(broad)]
        overlap = any(
            nt != "<bare>"
            and (bt == "<bare>" or (nt != bt and hier.is_subtype(nt, bt)))
            for nt in narrow_types
            for bt in broad_types
        )
        if not overlap:
            return
        broad_cleans = _cleanup_receivers(broad.body)
        # only resources the try body itself manipulates count — cleaning
        # self-state is the handler's own business
        relevant = {
            r for r in broad_cleans if r in try_resources and r != "self"
        }
        if not relevant:
            return
        narrow_names = _names_used(narrow)
        missed = sorted(r for r in relevant if r not in narrow_names)
        if not missed:
            return
        caught = ", ".join(t for t in narrow_types if t != "<bare>")
        findings.append(
            src.finding(
                self.name, narrow,
                f"handler for `{caught}` intercepts a subset of a later "
                f"handler's exceptions but never touches `{', '.join(missed)}`"
                " which that handler cleans up — the narrow path leaks the "
                "resource (the FileNotFoundError ⊂ OSError pool-poisoning "
                "class)",
            )
        )


def _names_used_in_body(body: List[ast.stmt]) -> Set[str]:
    out: Set[str] = set()
    for stmt in body:
        out |= _names_used(stmt)
    return out
