"""CLI: ``python -m tools.analyze [paths...] [--json] [--rule NAMES]
[--exclude PATTERN]``

Exit status 0 when every finding carries a suppression, 1 otherwise — the CI
gate is ``python -m tools.analyze raydp_tpu/ tools/ tests/conftest.py``
(the analyzer is self-hosted: its own source is swept).

``--rule`` takes a comma-separated list and is repeatable
(``--rule lock-order,blocking-under-lock``). ``--exclude`` removes files by
fnmatch pattern against the repo-relative path; default exclusions come from
``setup.cfg``'s ``[raydp-lint] exclude`` (the seeded-violation fixtures under
tests/analyze_fixtures/ live there, not as a hardcoded path check).

``--stats`` prints per-rule suppression counts; ``--write-budget`` commits
them to ``tools/analyze/suppression_budget.json``; ``--check-budget`` fails
when any rule suppresses more than its budgeted count — so a new suppression
only lands together with an explicit budget-file change in the same diff.

The RPC contract gate (tools/analyze/rpc.py) rides the same CLI:
``--write-contract`` serializes the extracted wire surface to
``tools/analyze/rpc_contract.json``; ``--check-contract`` fails when the live
surface drifted from the committed snapshot — so a protocol change only lands
together with an explicit, reviewable contract edit. ``--rpc-table`` prints
the human-readable surface table, ``--write-rpc-table`` splices it between
the rpc-surface markers in docs/cluster.md, and ``--check-rpc-table`` fails
when the committed table is stale.
"""

from __future__ import annotations

import argparse
import configparser
import json
import os
import sys
from collections import Counter

from tools.analyze.core import load_project, render_report, run_rules
from tools.analyze.rules import ALL_RULES, rules_by_name

#: Committed per-rule suppression counts (repo-relative). CI runs
#: ``--check-budget``: a suppression count may only grow when the same diff
#: updates this file — an explicit, reviewable act, never drift.
BUDGET_FILE = os.path.join("tools", "analyze", "suppression_budget.json")


def suppression_stats(findings) -> dict:
    """Per-rule count of SUPPRESSED findings, sorted by rule name."""
    counts = Counter(f.rule for f in findings if f.suppressed)
    return dict(sorted(counts.items()))


def check_budget(stats: dict, budget_path: str) -> list:
    """Lines describing budget violations (empty = within budget).

    Only growth fails: a rule suppressing MORE than its budgeted count means
    someone added a suppression without touching the committed budget. Counts
    below budget are fine (the ratchet is tightened by re-running
    ``--write-budget``, a separate deliberate act).
    """
    try:
        with open(budget_path, encoding="utf-8") as f:
            budget = json.load(f)
    except FileNotFoundError:
        return [
            f"suppression budget file missing: {budget_path} "
            "(create it with --write-budget)"
        ]
    except (OSError, ValueError) as exc:
        return [f"unreadable suppression budget {budget_path}: {exc}"]
    problems = []
    for rule, count in stats.items():
        allowed = budget.get(rule, 0)
        if count > allowed:
            problems.append(
                f"{rule}: {count} suppression(s), budget allows {allowed} — "
                "remove the new suppression or update "
                f"{os.path.relpath(budget_path)} in the same change"
            )
    return problems


def find_root(paths) -> str:
    """The directory whose setup.cfg governs this run: walk up from the
    first analyzed path (so the excludes apply no matter where the CLI is
    invoked from), falling back to the cwd."""
    for path in list(paths) + [os.getcwd()]:
        probe = os.path.abspath(path)
        if os.path.isfile(probe):
            probe = os.path.dirname(probe)
        while True:
            if os.path.isfile(os.path.join(probe, "setup.cfg")):
                return probe
            parent = os.path.dirname(probe)
            if parent == probe:
                break
            probe = parent
    return os.getcwd()


def config_excludes(root: str) -> list:
    """Exclusion patterns from ``[raydp-lint] exclude`` in setup.cfg (one
    per line or comma-separated)."""
    cfg = configparser.ConfigParser()
    try:
        cfg.read(os.path.join(root, "setup.cfg"))
    except configparser.Error:
        return []
    raw = cfg.get("raydp-lint", "exclude", fallback="")
    return [
        pattern.strip()
        for chunk in raw.splitlines()
        for pattern in chunk.split(",")
        if pattern.strip()
    ]


def spliced_doc(text: str, table: str) -> str:
    """The doc text with the generated table replacing whatever sits between
    the rpc-surface markers; raises ValueError when the markers are missing
    or inverted (the doc must carry them for the gate to have a home)."""
    from tools.analyze.rpc import RPC_TABLE_BEGIN, RPC_TABLE_END

    begin = text.find(RPC_TABLE_BEGIN)
    end = text.find(RPC_TABLE_END)
    if begin < 0 or end < 0 or end < begin:
        raise ValueError(
            f"rpc-surface markers missing or inverted "
            f"({RPC_TABLE_BEGIN!r} … {RPC_TABLE_END!r})"
        )
    return (
        text[: begin + len(RPC_TABLE_BEGIN)]
        + "\n\n" + table + "\n\n"
        + text[end:]
    )


def contract_main(args, project, root: str) -> int:
    """--write-contract / --check-contract / --rpc-table /
    --write-rpc-table / --check-rpc-table handling."""
    from tools.analyze import rpc as rpcmod

    surface = project.rpc_surface()
    contract_path = os.path.join(root, rpcmod.CONTRACT_FILE)
    docs_path = os.path.join(root, "docs", "cluster.md")
    if args.write_contract:
        with open(contract_path, "w", encoding="utf-8") as f:
            f.write(rpcmod.render_contract(rpcmod.build_contract(surface)))
        sys.stdout.write(f"wrote {os.path.relpath(contract_path)}\n")
    if args.check_contract:
        try:
            with open(contract_path, encoding="utf-8") as f:
                committed = json.load(f)
        except FileNotFoundError:
            sys.stderr.write(
                f"rpc contract missing: {contract_path} "
                "(create it with --write-contract)\n"
            )
            return 1
        except (OSError, ValueError) as exc:
            sys.stderr.write(f"unreadable rpc contract {contract_path}: {exc}\n")
            return 1
        problems = rpcmod.check_contract(surface, committed)
        for line in problems:
            sys.stderr.write(line + "\n")
        if problems:
            return 1
        sys.stdout.write(
            "raydp-lint: rpc wire surface matches the committed contract\n"
        )
    table = rpcmod.render_rpc_table(surface)
    if args.rpc_table:
        sys.stdout.write(table + "\n")
    if args.write_rpc_table or args.check_rpc_table:
        try:
            with open(docs_path, encoding="utf-8") as f:
                doc = f.read()
        except OSError as exc:
            sys.stderr.write(f"cannot read {docs_path}: {exc}\n")
            return 1
        try:
            updated = spliced_doc(doc, table)
        except ValueError as exc:
            sys.stderr.write(f"{docs_path}: {exc}\n")
            return 1
        if args.write_rpc_table:
            with open(docs_path, "w", encoding="utf-8") as f:
                f.write(updated)
            sys.stdout.write(f"wrote {os.path.relpath(docs_path)}\n")
        if args.check_rpc_table:
            if updated != doc:
                sys.stderr.write(
                    "docs/cluster.md RPC surface table is stale — regenerate "
                    "with --write-rpc-table and commit the diff\n"
                )
                return 1
            sys.stdout.write(
                "raydp-lint: docs/cluster.md RPC surface table is current\n"
            )
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="raydp-lint: project-specific static analysis",
    )
    parser.add_argument(
        "paths", nargs="*", default=["raydp_tpu"],
        help="files or directories to analyze (default: raydp_tpu)",
    )
    parser.add_argument("--json", action="store_true", help="JSON report")
    parser.add_argument(
        "--rule", action="append", default=None, metavar="NAMES",
        help="run only the named rule(s); comma-separated and repeatable "
        "(default: all rules)",
    )
    parser.add_argument(
        "--exclude", action="append", default=[], metavar="PATTERN",
        help="exclude files matching this fnmatch pattern (repeatable; "
        "added to setup.cfg [raydp-lint] exclude)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    parser.add_argument(
        "--stats", action="store_true",
        help="emit per-rule suppression counts instead of findings",
    )
    parser.add_argument(
        "--write-budget", action="store_true",
        help=f"write per-rule suppression counts to {BUDGET_FILE}",
    )
    parser.add_argument(
        "--check-budget", action="store_true",
        help="fail if any rule's suppression count exceeds the committed "
        f"budget in {BUDGET_FILE}",
    )
    parser.add_argument(
        "--write-contract", action="store_true",
        help="serialize the extracted RPC wire surface to "
        f"{os.path.join('tools', 'analyze', 'rpc_contract.json')}",
    )
    parser.add_argument(
        "--check-contract", action="store_true",
        help="fail if the live RPC wire surface drifted from the committed "
        "contract snapshot",
    )
    parser.add_argument(
        "--rpc-table", action="store_true",
        help="print the RPC surface table (op → caller files → handler)",
    )
    parser.add_argument(
        "--write-rpc-table", action="store_true",
        help="splice the generated RPC surface table into docs/cluster.md",
    )
    parser.add_argument(
        "--check-rpc-table", action="store_true",
        help="fail if docs/cluster.md's RPC surface table is stale",
    )
    args = parser.parse_args(argv)

    registry = rules_by_name()
    if args.list_rules:
        for name in sorted(registry):
            cls = registry[name]
            # some rules document themselves on the module, not the class
            doc = (cls.__doc__ or "").strip()
            if not doc:
                mod = sys.modules.get(cls.__module__)
                doc = (getattr(mod, "__doc__", "") or "").strip()
            first = doc.splitlines()[0] if doc else ""
            sys.stdout.write(f"{name}: {first}\n")
        return 0
    if args.rule:
        wanted = [
            name.strip()
            for spec in args.rule
            for name in spec.split(",")
            if name.strip()
        ]
        unknown = [r for r in wanted if r not in registry]
        if unknown:
            sys.stderr.write(
                f"unknown rule(s): {', '.join(unknown)} "
                f"(have: {', '.join(sorted(registry))})\n"
            )
            return 2
        rules = [registry[r]() for r in wanted]
    else:
        rules = [cls() for cls in ALL_RULES]

    root = find_root(args.paths)
    exclude = config_excludes(root) + list(args.exclude)
    project = load_project(args.paths, root=root, exclude=exclude)

    if (
        args.write_contract or args.check_contract or args.rpc_table
        or args.write_rpc_table or args.check_rpc_table
    ):
        # surface-only modes: no findings run (CI calls these as separate,
        # fast steps after the main sweep)
        return contract_main(args, project, root)

    findings = run_rules(project, rules)

    if args.stats or args.write_budget or args.check_budget:
        stats = suppression_stats(findings)
        budget_path = os.path.join(root, BUDGET_FILE)
        if args.stats:
            if args.json:
                sys.stdout.write(json.dumps(stats, indent=2) + "\n")
            else:
                for rule, count in stats.items():
                    sys.stdout.write(f"{rule}: {count}\n")
                sys.stdout.write(
                    f"raydp-lint: {sum(stats.values())} suppression(s) "
                    f"across {len(stats)} rule(s)\n"
                )
        if args.write_budget:
            with open(budget_path, "w", encoding="utf-8") as f:
                json.dump(stats, f, indent=2)
                f.write("\n")
            sys.stdout.write(f"wrote {os.path.relpath(budget_path)}\n")
        if args.check_budget:
            problems = check_budget(stats, budget_path)
            for line in problems:
                sys.stderr.write(line + "\n")
            if problems:
                return 1
            sys.stdout.write("raydp-lint: suppression counts within budget\n")
        return 0

    report, code = render_report(findings, as_json=args.json)
    sys.stdout.write(report + "\n")
    return code


if __name__ == "__main__":
    sys.exit(main())
