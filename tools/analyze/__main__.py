"""CLI: ``python -m tools.analyze [paths...] [--json] [--rule NAMES]
[--exclude PATTERN]``

Exit status 0 when every finding carries a suppression, 1 otherwise — the CI
gate is ``python -m tools.analyze raydp_tpu/ tools/ tests/conftest.py``
(the analyzer is self-hosted: its own source is swept).

``--rule`` takes a comma-separated list and is repeatable
(``--rule lock-order,blocking-under-lock``). ``--exclude`` removes files by
fnmatch pattern against the repo-relative path; default exclusions come from
``setup.cfg``'s ``[raydp-lint] exclude`` (the seeded-violation fixtures under
tests/analyze_fixtures/ live there, not as a hardcoded path check).

``--stats`` prints per-rule suppression counts; ``--write-budget`` commits
them to ``tools/analyze/suppression_budget.json``; ``--check-budget`` fails
when any rule suppresses more than its budgeted count — so a new suppression
only lands together with an explicit budget-file change in the same diff.
"""

from __future__ import annotations

import argparse
import configparser
import json
import os
import sys
from collections import Counter

from tools.analyze.core import load_project, render_report, run_rules
from tools.analyze.rules import ALL_RULES, rules_by_name

#: Committed per-rule suppression counts (repo-relative). CI runs
#: ``--check-budget``: a suppression count may only grow when the same diff
#: updates this file — an explicit, reviewable act, never drift.
BUDGET_FILE = os.path.join("tools", "analyze", "suppression_budget.json")


def suppression_stats(findings) -> dict:
    """Per-rule count of SUPPRESSED findings, sorted by rule name."""
    counts = Counter(f.rule for f in findings if f.suppressed)
    return dict(sorted(counts.items()))


def check_budget(stats: dict, budget_path: str) -> list:
    """Lines describing budget violations (empty = within budget).

    Only growth fails: a rule suppressing MORE than its budgeted count means
    someone added a suppression without touching the committed budget. Counts
    below budget are fine (the ratchet is tightened by re-running
    ``--write-budget``, a separate deliberate act).
    """
    try:
        with open(budget_path, encoding="utf-8") as f:
            budget = json.load(f)
    except FileNotFoundError:
        return [
            f"suppression budget file missing: {budget_path} "
            "(create it with --write-budget)"
        ]
    except (OSError, ValueError) as exc:
        return [f"unreadable suppression budget {budget_path}: {exc}"]
    problems = []
    for rule, count in stats.items():
        allowed = budget.get(rule, 0)
        if count > allowed:
            problems.append(
                f"{rule}: {count} suppression(s), budget allows {allowed} — "
                "remove the new suppression or update "
                f"{os.path.relpath(budget_path)} in the same change"
            )
    return problems


def find_root(paths) -> str:
    """The directory whose setup.cfg governs this run: walk up from the
    first analyzed path (so the excludes apply no matter where the CLI is
    invoked from), falling back to the cwd."""
    for path in list(paths) + [os.getcwd()]:
        probe = os.path.abspath(path)
        if os.path.isfile(probe):
            probe = os.path.dirname(probe)
        while True:
            if os.path.isfile(os.path.join(probe, "setup.cfg")):
                return probe
            parent = os.path.dirname(probe)
            if parent == probe:
                break
            probe = parent
    return os.getcwd()


def config_excludes(root: str) -> list:
    """Exclusion patterns from ``[raydp-lint] exclude`` in setup.cfg (one
    per line or comma-separated)."""
    cfg = configparser.ConfigParser()
    try:
        cfg.read(os.path.join(root, "setup.cfg"))
    except configparser.Error:
        return []
    raw = cfg.get("raydp-lint", "exclude", fallback="")
    return [
        pattern.strip()
        for chunk in raw.splitlines()
        for pattern in chunk.split(",")
        if pattern.strip()
    ]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="raydp-lint: project-specific static analysis",
    )
    parser.add_argument(
        "paths", nargs="*", default=["raydp_tpu"],
        help="files or directories to analyze (default: raydp_tpu)",
    )
    parser.add_argument("--json", action="store_true", help="JSON report")
    parser.add_argument(
        "--rule", action="append", default=None, metavar="NAMES",
        help="run only the named rule(s); comma-separated and repeatable "
        "(default: all rules)",
    )
    parser.add_argument(
        "--exclude", action="append", default=[], metavar="PATTERN",
        help="exclude files matching this fnmatch pattern (repeatable; "
        "added to setup.cfg [raydp-lint] exclude)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    parser.add_argument(
        "--stats", action="store_true",
        help="emit per-rule suppression counts instead of findings",
    )
    parser.add_argument(
        "--write-budget", action="store_true",
        help=f"write per-rule suppression counts to {BUDGET_FILE}",
    )
    parser.add_argument(
        "--check-budget", action="store_true",
        help="fail if any rule's suppression count exceeds the committed "
        f"budget in {BUDGET_FILE}",
    )
    args = parser.parse_args(argv)

    registry = rules_by_name()
    if args.list_rules:
        for name in sorted(registry):
            doc = (registry[name].__doc__ or "").strip().splitlines()[0]
            sys.stdout.write(f"{name}: {doc}\n")
        return 0
    if args.rule:
        wanted = [
            name.strip()
            for spec in args.rule
            for name in spec.split(",")
            if name.strip()
        ]
        unknown = [r for r in wanted if r not in registry]
        if unknown:
            sys.stderr.write(
                f"unknown rule(s): {', '.join(unknown)} "
                f"(have: {', '.join(sorted(registry))})\n"
            )
            return 2
        rules = [registry[r]() for r in wanted]
    else:
        rules = [cls() for cls in ALL_RULES]

    root = find_root(args.paths)
    exclude = config_excludes(root) + list(args.exclude)
    project = load_project(args.paths, root=root, exclude=exclude)
    findings = run_rules(project, rules)

    if args.stats or args.write_budget or args.check_budget:
        stats = suppression_stats(findings)
        budget_path = os.path.join(root, BUDGET_FILE)
        if args.stats:
            if args.json:
                sys.stdout.write(json.dumps(stats, indent=2) + "\n")
            else:
                for rule, count in stats.items():
                    sys.stdout.write(f"{rule}: {count}\n")
                sys.stdout.write(
                    f"raydp-lint: {sum(stats.values())} suppression(s) "
                    f"across {len(stats)} rule(s)\n"
                )
        if args.write_budget:
            with open(budget_path, "w", encoding="utf-8") as f:
                json.dump(stats, f, indent=2)
                f.write("\n")
            sys.stdout.write(f"wrote {os.path.relpath(budget_path)}\n")
        if args.check_budget:
            problems = check_budget(stats, budget_path)
            for line in problems:
                sys.stderr.write(line + "\n")
            if problems:
                return 1
            sys.stdout.write("raydp-lint: suppression counts within budget\n")
        return 0

    report, code = render_report(findings, as_json=args.json)
    sys.stdout.write(report + "\n")
    return code


if __name__ == "__main__":
    sys.exit(main())
