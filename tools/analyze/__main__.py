"""CLI: ``python -m tools.analyze [paths...] [--json] [--rule NAME]...``

Exit status 0 when every finding carries a suppression, 1 otherwise — the CI
gate is exactly ``python -m tools.analyze raydp_tpu/``.
"""

from __future__ import annotations

import argparse
import sys

from tools.analyze.core import load_project, render_report, run_rules
from tools.analyze.rules import ALL_RULES, rules_by_name


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="raydp-lint: project-specific static analysis",
    )
    parser.add_argument(
        "paths", nargs="*", default=["raydp_tpu"],
        help="files or directories to analyze (default: raydp_tpu)",
    )
    parser.add_argument("--json", action="store_true", help="JSON report")
    parser.add_argument(
        "--rule", action="append", default=None, metavar="NAME",
        help="run only the named rule (repeatable); default: all rules",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    args = parser.parse_args(argv)

    registry = rules_by_name()
    if args.list_rules:
        for name in sorted(registry):
            doc = (registry[name].__doc__ or "").strip().splitlines()[0]
            sys.stdout.write(f"{name}: {doc}\n")
        return 0
    if args.rule:
        unknown = [r for r in args.rule if r not in registry]
        if unknown:
            sys.stderr.write(
                f"unknown rule(s): {', '.join(unknown)} "
                f"(have: {', '.join(sorted(registry))})\n"
            )
            return 2
        rules = [registry[r]() for r in args.rule]
    else:
        rules = [cls() for cls in ALL_RULES]

    project = load_project(args.paths)
    findings = run_rules(project, rules)
    report, code = render_report(findings, as_json=args.json)
    sys.stdout.write(report + "\n")
    return code


if __name__ == "__main__":
    sys.exit(main())
