"""Package-wide lock-object resolution, shared by the concurrency rules
(``lock-order``, ``blocking-under-lock``).

Python has no static lock types, so the model is built from the package's own
idioms:

- **self-attr locks** — ``self.X = threading.Lock()/RLock()/Semaphore()`` (or
  the sanitizer's ``named_lock(...)`` wrapper) anywhere in a class body
  registers lock ``<module>.<Class>.X``;
- **module globals** — ``_lib_lock = threading.Lock()`` at module top level
  registers ``<module>.<name>`` (resolution of a bare name is module-local:
  two modules' ``_lock`` globals are distinct locks);
- **Condition aliasing** — ``self.C = threading.Condition(self.X)`` makes
  ``self.C`` the SAME lock node as ``self.X`` (a Condition over a lock *is*
  that mutex: the head's ``actor_state_cond`` wraps ``head.lock``). A bare
  ``Condition()`` is its own lock.

Resolution of a ``with <expr>`` / annotation spec:

- ``self.X`` inside class ``C`` → ``C``'s lock ``X`` if ``C`` declares one,
  else the unique declaring class if exactly one class in the package has a
  lock attr ``X`` (inheritance);
- bare ``NAME`` → the current module's global lock ``NAME``;
- ``obj.X`` (non-self) → the unique declaring class's ``X``; ambiguous attr
  names (``_lock`` exists on several classes) resolve to nothing —
  under-reporting beats mis-attributing an edge.

``# guarded-by: <lock> held`` def annotations (the PR 4 vocabulary) mark a
function's entry held-set; alternates (``lockA|lockB``) resolve each part and
usually collapse to one node via Condition aliasing.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from tools.analyze.core import Project, SourceFile, dotted_name
from tools.analyze.rules.guarded_by import _annotations

_LOCK_CTOR_SUFFIXES = ("Lock", "RLock", "Semaphore", "BoundedSemaphore")
_WRAPPER_NAMES = ("named_lock",)


def module_of(src: SourceFile) -> str:
    """Module key from the FULL repo-relative path, not the basename: the
    repo has obs/metrics.py AND estimator/metrics.py (and many __init__.py),
    and a basename key would fuse their lock namespaces — a global named
    ``_lock`` in one would resolve against the other's."""
    path = src.display_path
    if path.endswith(".py"):
        path = path[: -len(".py")]
    return path.replace(os.sep, ".").replace("/", ".").lstrip(".")


def _is_lock_ctor(value: ast.AST) -> bool:
    """Is this expression a lock constructor (incl. the named_lock wrapper)?"""
    if not isinstance(value, ast.Call):
        return False
    name = dotted_name(value.func)
    if name is None:
        return False
    terminal = name.split(".")[-1]
    if terminal in _LOCK_CTOR_SUFFIXES:
        return True
    if terminal in _WRAPPER_NAMES:
        return True
    return False


def _condition_target(value: ast.AST) -> Optional[ast.AST]:
    """For ``threading.Condition(<lock-expr>)`` return the lock expr."""
    if not isinstance(value, ast.Call):
        return None
    name = dotted_name(value.func)
    if name is None or name.split(".")[-1] != "Condition":
        return None
    if value.args:
        return value.args[0]
    return None


class LockModel:
    """Lock identities + aliases discovered across the whole project."""

    def __init__(self, project: Project):
        # (module, class, attr) -> canonical id;  (module, name) -> canonical
        self._class_attrs: Dict[Tuple[str, str], Dict[str, str]] = {}
        self._globals: Dict[Tuple[str, str], str] = {}
        self._attr_owners: Dict[str, Set[str]] = {}  # attr -> {canonical}
        self._alias: Dict[str, str] = {}  # canonical -> canonical
        self._discover(project)

    # ---------- discovery ----------

    def _discover(self, project: Project) -> None:
        pending_aliases: List[Tuple[str, str, str, ast.AST, Optional[str]]] = []
        for src in project:
            if src.tree is None:
                continue
            module = module_of(src)
            for stmt in src.tree.body:
                targets = _assign_targets(stmt)
                for target, value in targets:
                    if not isinstance(target, ast.Name):
                        continue
                    if _is_lock_ctor(value) or _wraps_lock_ctor(value):
                        self._globals[(module, target.id)] = f"{module}.{target.id}"
                    cond = _condition_target(value)
                    if cond is not None:
                        pending_aliases.append(
                            (module, "", target.id, cond, None)
                        )
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                attrs = self._class_attrs.setdefault((module, node.name), {})
                for sub in ast.walk(node):
                    for target, value in _assign_targets(sub):
                        if not (
                            isinstance(target, ast.Attribute)
                            and isinstance(target.value, ast.Name)
                            and target.value.id == "self"
                        ):
                            continue
                        if _is_lock_ctor(value) or _wraps_lock_ctor(value):
                            canonical = f"{module}.{node.name}.{target.attr}"
                            attrs[target.attr] = canonical
                            self._attr_owners.setdefault(
                                target.attr, set()
                            ).add(canonical)
                        cond = _condition_target(value)
                        if cond is not None:
                            pending_aliases.append(
                                (module, node.name, target.attr, cond, None)
                            )
        # resolve Condition aliases now every plain lock is known
        for module, cls, attr, cond_expr, _ in pending_aliases:
            target = self.resolve(cond_expr, cls or None, module)
            if target is None:
                continue  # Condition over an unknown lock: its own node
            if cls:
                canonical = f"{module}.{cls}.{attr}"
                self._class_attrs.setdefault((module, cls), {})[attr] = canonical
                self._attr_owners.setdefault(attr, set()).add(canonical)
            else:
                canonical = f"{module}.{attr}"
                self._globals[(module, attr)] = canonical
            self._alias[canonical] = self._canon(target)

    # ---------- resolution ----------

    def _canon(self, lock_id: str) -> str:
        seen = set()
        while lock_id in self._alias and lock_id not in seen:
            seen.add(lock_id)
            lock_id = self._alias[lock_id]
        return lock_id

    def _unique_attr(self, attr: str) -> Optional[str]:
        owners = self._attr_owners.get(attr)
        if owners is not None and len(owners) == 1:
            return self._canon(next(iter(owners)))
        return None

    def resolve(
        self,
        expr_or_name,
        class_name: Optional[str],
        module: str,
    ) -> Optional[str]:
        """Canonical lock id for a ``with``-expression / annotation part, or
        None when it does not resolve to a known lock."""
        if isinstance(expr_or_name, str):
            name = expr_or_name
        else:
            name = dotted_name(expr_or_name)
        if not name:
            return None
        parts = name.split(".")
        if parts[0] == "self" and len(parts) == 2 and class_name:
            attrs = self._class_attrs.get((module, class_name), {})
            if parts[1] in attrs:
                return self._canon(attrs[parts[1]])
            return self._unique_attr(parts[1])
        if len(parts) == 1:
            canonical = self._globals.get((module, parts[0]))
            return self._canon(canonical) if canonical else None
        # obj.attr / pkg.mod.attr: attribute name must be unambiguous
        return self._unique_attr(parts[-1])

    def resolve_spec(
        self, spec: str, class_name: Optional[str], module: str
    ) -> Set[str]:
        """Resolve a guarded-by spec (``self.lock|self.actor_state_cond``)."""
        out: Set[str] = set()
        for part in spec.split("|"):
            part = part.strip()
            if not part:
                continue
            resolved = self.resolve(part, class_name, module)
            if resolved is not None:
                out.add(resolved)
        return out


def _assign_targets(stmt: ast.AST) -> List[Tuple[ast.AST, ast.AST]]:
    if isinstance(stmt, ast.Assign) and stmt.value is not None:
        return [(t, stmt.value) for t in stmt.targets]
    if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        return [(stmt.target, stmt.value)]
    return []


def _wraps_lock_ctor(value: ast.AST) -> bool:
    """``named_lock("name", threading.RLock())`` — the wrapper itself already
    counts, but also accept any call whose ARGUMENT is a lock ctor (e.g. a
    future wrapper the model does not know by name)."""
    if not isinstance(value, ast.Call):
        return False
    return any(_is_lock_ctor(arg) for arg in value.args)


def entry_held(
    func: ast.AST,
    annotations: Dict[int, Tuple[str, bool]],
    model: LockModel,
    class_name: Optional[str],
    module: str,
    src: SourceFile,
) -> List[Tuple[str, str]]:
    """(canonical, site description) entries a function holds on entry, per
    its ``# guarded-by: <lock> held`` annotation."""
    annot = annotations.get(getattr(func, "lineno", -1))
    if annot is None or not annot[1]:
        return []
    held = []
    for canonical in sorted(model.resolve_spec(annot[0], class_name, module)):
        held.append(
            (
                canonical,
                f"held on entry to {getattr(func, 'name', '<lambda>')} "
                f"({src.display_path}:{func.lineno}, guarded-by annotation)",
            )
        )
    return held


def get_lock_model(project: Project) -> LockModel:
    """One LockModel per project: both concurrency rules need it, and the
    discovery pass walks every file's AST — build it once, cache it on the
    project object."""
    model = getattr(project, "_lock_model", None)
    if model is None:
        model = LockModel(project)
        project._lock_model = model  # type: ignore[attr-defined]
    return model


class HeldStackWalker(ast.NodeVisitor):
    """Shared held-stack maintenance for the concurrency rules: resolves
    each ``with`` item to a lock, skips reentrant re-acquisition (RLock /
    Condition alias already in the held set), pushes for the body and pops
    after, and RESETS the context inside nested defs/lambdas (closures run
    later, possibly on another thread — only their own ``... held``
    annotation seeds their entry set). Items of one ``with a, b:`` enter
    sequentially, so item *i*'s context expression is visited (and its lock
    ordered) with items ``< i`` already held.

    Subclasses implement ``_clone(func_name, held)`` (a fresh walker for a
    nested scope) and hook ``on_acquire(canonical, node)``, called once per
    NEWLY-acquired lock with ``self.held`` reflecting everything held at
    that moment."""

    def __init__(
        self,
        src: SourceFile,
        model: LockModel,
        annotations: Dict[int, Tuple[str, bool]],
        class_name: Optional[str],
        module: str,
        func_name: str,
        held: List[Tuple[str, str]],
    ):
        self.src = src
        self.model = model
        self.annotations = annotations
        self.class_name = class_name
        self.module = module
        self.func_name = func_name
        self.held = held  # [(canonical, acquisition-site description)]

    # ---- subclass hooks ----

    def on_acquire(self, canonical: str, node: ast.With) -> None:
        """Called for each newly-acquired lock, before it joins self.held."""

    def _clone(self, func_name: str, held: List[Tuple[str, str]]):
        raise NotImplementedError

    # ---- shared walking ----

    def _acquire_site(self, node: ast.AST) -> str:
        return (
            f"acquired at {self.src.display_path}:{node.lineno} "
            f"in {self.func_name}"
        )

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            # evaluated while earlier items' locks are already held
            self.visit(item.context_expr)
            canonical = self.model.resolve(
                item.context_expr, self.class_name, self.module
            )
            if canonical is None or any(
                h[0] == canonical for h in self.held
            ):
                # unknown lock, or reentrant re-acquisition (RLock /
                # Condition alias): no new ordering information
                continue
            self.on_acquire(canonical, node)
            self.held.append((canonical, self._acquire_site(node)))
            pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        if pushed:
            del self.held[-pushed:]

    visit_AsyncWith = visit_With

    def _enter_nested(self, node) -> None:
        inner_held = entry_held(
            node, self.annotations, self.model, self.class_name,
            self.module, self.src,
        )
        inner = self._clone(getattr(node, "name", "<lambda>"), inner_held)
        body = node.body if isinstance(node.body, list) else [node.body]
        for stmt in body:
            inner.visit(stmt)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter_nested(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        inner = self._clone("<lambda>", [])
        inner.visit(node.body)


def iter_class_functions(tree: ast.AST):
    """Yield (class_name_or_None, funcdef) for every top-level function and
    every method, attributing methods to their class."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield node.name, sub


__all__ = [
    "LockModel",
    "get_lock_model",
    "HeldStackWalker",
    "module_of",
    "entry_held",
    "iter_class_functions",
    "_annotations",
]
