"""raydp-lint: project-specific static analysis (``python -m tools.analyze``).

Each shipped PR's postmortem became a machine-checked invariant here, in the
lockset/Eraser spirit of checking the *property* instead of re-reproducing the
bug: donated jit inputs must not alias externally-owned host buffers
(donation-aliasing — the PR 2 streaming-NaN class), by-name RPC dispatch must
stay closed over ops and arities (rpc-protocol), exception handlers must not
swallow silently (swallowed-exceptions — the ``store.delete_failures`` class),
lock-guarded attributes must be touched under their lock (guarded-by — the
``_reap_after_kill`` double-read class), and runtime diagnostics must go
through the structured logger (print-diagnostics).

See docs/analysis.md for the rule catalogue and suppression syntax.
"""

from tools.analyze.core import Finding, Project, run_rules  # noqa: F401
