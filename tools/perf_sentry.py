"""Bench-regression sentry: the BENCH_r* trajectory as a machine-checked ledger.

Until PR 15 the bench trajectory lived as tribal knowledge ("trust
interleaved medians, not single samples" — the r06 lesson) and perf gates
as hand-pinned per-release constants inside ``tools/perf_smoke.py``. This
tool turns every committed ``BENCH_r*.json`` snapshot into one
schema-validated **ledger** and derives, per tracked stat:

- a **baseline value** — the median of the newest up-to-3 releases that
  report the stat (median-of-releases: one noisy snapshot cannot move the
  baseline, the ledger-level form of the interleaved-median rule);
- a **noise band** — 2x the median absolute relative release-to-release
  delta over the stat's history, clamped to [``MIN_BAND``, ``MAX_BAND``].
  The floor encodes the r06 incident: a 2-core box drifts ±25% between
  identical runs, so no stat gets a band tighter than what box noise has
  actually produced; the clamp keeps a stat with one wild historical swing
  from becoming ungateable.

``--write`` emits ``BENCH_BASELINE.json`` (committed; ``tools/perf_smoke``
reads its thresholds from it). ``--check`` recomputes the ledger from the
BENCH files and fails when the NEWEST release regresses beyond baseline +
band on any stat (direction-aware: "lower is better" stats gate upward,
"higher is better" downward) — the CI gate. Stats a release doesn't report
are skipped, never failed: the ledger spans releases that predate most
probes.

Usage:
    python -m tools.perf_sentry --write   [--ledger BENCH_BASELINE.json]
    python -m tools.perf_sentry --check   [--ledger BENCH_BASELINE.json]
    python -m tools.perf_sentry           # print the trend table
"""
# raydp-lint: disable-file=print-diagnostics (standalone CI tool: its stdout IS the report, there is no obs role to tag)

from __future__ import annotations

import glob
import json
import os
import re
import statistics
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LEDGER_FORMAT = "raydp-bench-ledger-v1"
DEFAULT_LEDGER = "BENCH_BASELINE.json"

MIN_BAND = 0.25  # the r06 floor: box noise alone produces ±25% swings
MAX_BAND = 0.60  # one wild historical swing must not make a stat ungateable
BASELINE_POINTS = 3  # median of the newest N reporting releases

# tracked stats: name -> (dotted path into the parsed bench JSON, direction)
# direction "higher" = regressions are DROPS, "lower" = regressions are RISES
STATS: Dict[str, Tuple[str, str]] = {
    "e2e_sps": ("value", "higher"),
    "vs_baseline": ("vs_baseline", "higher"),
    "train_vs_pure": ("detail.train_vs_pure", "higher"),
    "etl_query_s": ("detail.etl_query_s", "lower"),
    "burst_p50_ms": ("detail.burst_p50_ms", "lower"),
    "burst_p99_ms": ("detail.burst_p99_ms", "lower"),
    "plan_cache_hit_rate": ("detail.plan_cache_hit_rate", "higher"),
    "cluster_boot_s": ("detail.cluster_boot_s", "lower"),
    "streaming_vs_scan": ("detail.streaming_vs_scan", "higher"),
    "streaming_hybrid_vs_scan": ("detail.streaming_hybrid_vs_scan", "higher"),
    "consumer_idle_s": (
        "detail.streaming_pipeline.consumer_idle_s", "lower"
    ),
    "dlrm_train_vs_pure": ("detail.dlrm.train_vs_pure", "higher"),
    "serve_p99_ms": ("detail.serving_probe.p99_ms", "lower"),
    "serve_rps": ("detail.serving_probe.sustained_rps", "higher"),
    "decode_tokens_per_sec": (
        "detail.decode_serving_probe.decode_tokens_per_sec", "higher"
    ),
    "decode_token_p99_ms": (
        "detail.decode_serving_probe.token_p99_ms", "lower"
    ),
    "tenant_p99_ratio": ("detail.tenant_isolation_probe.p99_ratio", "lower"),
    "lm_mfu": ("detail.lm.mfu", "higher"),
    "fit_mfu": ("detail.fit_profile_probe.mfu_live", "higher"),
    "crosshost_shuffle_s": (
        "detail.crosshost_shuffle_probe.shuffle_wall_s", "lower"
    ),
    "crosshost_locality_hit_rate": (
        "detail.crosshost_shuffle_probe.locality_hit_rate", "higher"
    ),
}


# ---------------------------------------------------------------------------
# extraction: parsed JSON when a snapshot carries it, regex over the stdout
# tail otherwise (old snapshots truncate the front of the tail)
# ---------------------------------------------------------------------------


def _dotted(parsed: Optional[dict], path: str) -> Optional[float]:
    node: Any = parsed
    for part in path.split("."):
        if not isinstance(node, dict):
            return None
        node = node.get(part)
    if isinstance(node, bool) or not isinstance(node, (int, float)):
        return None
    return float(node)


def _tail_regex(tail: str, key: str) -> Optional[float]:
    # first occurrence is the NYCTaxi slice — the perf_smoke convention
    found = re.search(rf'"{key}": (-?[0-9.]+)', tail)
    try:
        return float(found.group(1)) if found else None
    except ValueError:
        return None


def _parse_snapshot(path: str) -> Tuple[Optional[int], Dict[str, float]]:
    """(release number, {stat: value}) for one BENCH_r*.json file."""
    with open(path) as f:
        raw = json.load(f)
    tail = raw.get("tail", "") or ""
    parsed = raw.get("parsed")
    if parsed is None:
        for line in reversed(tail.strip().splitlines()):
            try:
                candidate = json.loads(line)
            except ValueError:  # raydp-lint: disable=swallowed-exceptions (scanning the stdout tail for its one JSON line; non-JSON lines are expected)
                continue
            if isinstance(candidate, dict) and "metric" in candidate:
                parsed = candidate
                break
    release = None
    found = re.search(r"BENCH_r(\d+)\.json$", path)
    if found:
        release = int(found.group(1))
    stats: Dict[str, float] = {}
    for name, (dotted_path, _direction) in STATS.items():
        value = _dotted(parsed, dotted_path)
        if value is None:
            value = _tail_regex(tail, dotted_path.rsplit(".", 1)[-1])
        if value is not None:
            stats[name] = value
    return release, stats


def build_ledger(repo: str = REPO) -> dict:
    """All committed BENCH_r*.json snapshots as one ledger dict (releases
    ordered by release number)."""
    releases: List[dict] = []
    for path in glob.glob(os.path.join(repo, "BENCH_r*.json")):
        release, stats = _parse_snapshot(path)
        if release is None or not stats:
            continue
        releases.append({
            "release": f"r{release:02d}",
            "n": release,
            "stats": stats,
        })
    releases.sort(key=lambda r: r["n"])
    return {
        "format": LEDGER_FORMAT,
        "directions": {name: d for name, (_p, d) in STATS.items()},
        "releases": releases,
        "baseline": derive_baselines(releases),
    }


# ---------------------------------------------------------------------------
# trend statistics
# ---------------------------------------------------------------------------


def _series(releases: List[dict], stat: str) -> List[Tuple[int, float]]:
    return [
        (r["n"], r["stats"][stat]) for r in releases if stat in r["stats"]
    ]


def noise_band(values: List[float]) -> float:
    """Noise band from successive relative deltas, clamped to
    [MIN_BAND, MAX_BAND]. Fewer than 3 points = MAX_BAND (one delta is a
    sample, not a distribution — exactly the single-sample trap the r06
    incident taught)."""
    if len(values) < 3:
        return MAX_BAND
    deltas = [
        abs(b - a) / abs(a)
        for a, b in zip(values[:-1], values[1:])
        if a
    ]
    if not deltas:
        return MAX_BAND
    return max(MIN_BAND, min(MAX_BAND, 2.0 * statistics.median(deltas)))


def derive_baselines(releases: List[dict]) -> Dict[str, dict]:
    """Per-stat baseline value + noise band from the release series."""
    out: Dict[str, dict] = {}
    for stat, (_path, direction) in STATS.items():
        series = _series(releases, stat)
        if not series:
            continue
        values = [v for _, v in series]
        recent = values[-BASELINE_POINTS:]
        out[stat] = {
            "value": statistics.median(recent),
            "band": round(noise_band(values), 4),
            "direction": direction,
            "points": len(values),
            "newest_release": f"r{series[-1][0]:02d}",
        }
    return out


def check_release(stats: Dict[str, float],
                  baseline: Dict[str, dict]) -> List[str]:
    """Direction-aware regression check of one release's stats against the
    baseline+band; returns human-readable failures (empty = pass)."""
    failures: List[str] = []
    for stat, value in stats.items():
        ref = baseline.get(stat)
        if ref is None or not ref.get("value"):
            continue
        base, band = float(ref["value"]), float(ref["band"])
        if ref["direction"] == "lower":
            limit = base * (1.0 + band)
            if value > limit:
                failures.append(
                    f"{stat}: {value:.4g} exceeds {limit:.4g} "
                    f"(baseline {base:.4g} + {band:.0%} noise band)"
                )
        else:
            limit = base * (1.0 - band)
            if value < limit:
                failures.append(
                    f"{stat}: {value:.4g} below {limit:.4g} "
                    f"(baseline {base:.4g} - {band:.0%} noise band)"
                )
    return failures


# ---------------------------------------------------------------------------
# schema validation (the ledger is a committed contract, not a cache)
# ---------------------------------------------------------------------------


def validate_ledger(ledger: dict) -> None:
    """Raise ValueError on any structural problem — a corrupt committed
    ledger must fail loudly, not gate against garbage."""
    if not isinstance(ledger, dict) or ledger.get("format") != LEDGER_FORMAT:
        raise ValueError(
            f"ledger format is not {LEDGER_FORMAT!r}: "
            f"{ledger.get('format') if isinstance(ledger, dict) else ledger!r}"
        )
    releases = ledger.get("releases")
    if not isinstance(releases, list) or not releases:
        raise ValueError("ledger has no releases")
    last_n = None
    for record in releases:
        if not isinstance(record, dict):
            raise ValueError(f"release record is not a dict: {record!r}")
        n = record.get("n")
        if not isinstance(n, int):
            raise ValueError(f"release {record.get('release')!r}: bad n={n!r}")
        if last_n is not None and n <= last_n:
            raise ValueError("releases are not strictly ordered by n")
        last_n = n
        stats = record.get("stats")
        if not isinstance(stats, dict) or not stats:
            raise ValueError(f"release r{n}: empty stats")
        for key, value in stats.items():
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                raise ValueError(f"release r{n}: stat {key}={value!r} not numeric")
    baseline = ledger.get("baseline")
    if not isinstance(baseline, dict) or not baseline:
        raise ValueError("ledger has no baseline section")
    for stat, ref in baseline.items():
        if ref.get("direction") not in ("higher", "lower"):
            raise ValueError(f"baseline {stat}: bad direction {ref.get('direction')!r}")
        for key in ("value", "band"):
            value = ref.get(key)
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                raise ValueError(f"baseline {stat}: {key}={value!r} not numeric")


def load_baseline(ledger_path: Optional[str] = None) -> Optional[Dict[str, dict]]:
    """The committed baseline section, validated — or None when the ledger
    file is absent (callers keep their hardcoded fallbacks)."""
    path = ledger_path or os.path.join(REPO, DEFAULT_LEDGER)
    try:
        with open(path) as f:
            ledger = json.load(f)
    except (OSError, ValueError):
        return None
    validate_ledger(ledger)
    return ledger["baseline"]


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def format_trend(ledger: dict) -> str:
    lines = [f"{'stat':<26} {'dir':<6} {'baseline':>12} {'band':>6} "
             f"{'newest':>12}  trajectory"]
    for stat, ref in sorted(ledger["baseline"].items()):
        series = _series(ledger["releases"], stat)
        trajectory = " ".join(f"r{n}:{v:.3g}" for n, v in series[-6:])
        lines.append(
            f"{stat:<26} {ref['direction']:<6} {ref['value']:>12.4g} "
            f"{ref['band']:>6.0%} {series[-1][1]:>12.4g}  {trajectory}"
        )
    return "\n".join(lines)


def main(argv: List[str]) -> int:
    ledger_path = os.path.join(REPO, DEFAULT_LEDGER)
    if "--ledger" in argv:
        ledger_path = argv[argv.index("--ledger") + 1]
    ledger = build_ledger()
    if not ledger["releases"]:
        print("PERF-SENTRY FAIL: no BENCH_r*.json snapshots found",
              file=sys.stderr)
        return 1
    validate_ledger(ledger)

    if "--write" in argv:
        with open(ledger_path, "w") as f:
            json.dump(ledger, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {ledger_path} "
              f"({len(ledger['releases'])} releases, "
              f"{len(ledger['baseline'])} gated stats)")
        return 0

    if "--check" in argv:
        committed = load_baseline(ledger_path)
        if committed is None:
            print(
                f"PERF-SENTRY FAIL: no committed ledger at {ledger_path} "
                "(run --write and commit it)",
                file=sys.stderr,
            )
            return 1
        newest = ledger["releases"][-1]
        # gate the NEWEST release against the COMMITTED baseline — the
        # thresholds pinned when the ledger was last accepted (--write).
        # A fresh BENCH_rNN lands, --check gates it against the prior
        # era's bands; accepting it means re-running --write, which is a
        # reviewed diff on BENCH_BASELINE.json — never a silent ratchet.
        failures = check_release(newest["stats"], committed)
        if failures:
            for failure in failures:
                print(
                    f"PERF-SENTRY FAIL [{newest['release']}]: {failure}",
                    file=sys.stderr,
                )
            return 1
        print(
            f"PERF-SENTRY OK: {newest['release']} within noise bands on "
            f"{len(newest['stats'])} stats "
            f"({len(ledger['releases'])} releases in ledger)"
        )
        return 0

    print(format_trend(ledger))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
