"""CI smoke: one traced ETL→fit run, exported and validated as Perfetto JSON.

Run: ``python tools/trace_smoke.py [out.json]``. Asserts the trace contains
complete spans from at least three distinct processes (driver, head, and at
least one executor actor) linked under a shared trace id — the end-to-end
guarantee the tracing plane makes. CI uploads the resulting file as a build
artifact so any run's timeline can be opened in https://ui.perfetto.dev.
"""
# raydp-lint: disable-file=print-diagnostics (standalone CI tool: its stdout IS the report, there is no obs role to tag)

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("RAYDP_TPU_TRACE", "1")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np
import pandas as pd

import raydp_tpu
from raydp_tpu.estimator import JaxEstimator
from raydp_tpu.etl import functions as F
from raydp_tpu.exchange import dataframe_to_dataset


def main() -> None:
    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(1)(nn.relu(nn.Dense(16)(x)))

    session = raydp_tpu.init_etl(
        "trace-smoke", num_executors=2, executor_cores=1,
        executor_memory="300M",
    )
    rng = np.random.default_rng(0)
    pdf = pd.DataFrame(
        {
            "x": rng.random(2048).astype("float32"),
            "y": rng.random(2048).astype("float32"),
        }
    )
    df = session.from_pandas(pdf, num_partitions=4).with_column(
        "z", F.col("x") * 2 + F.col("y")
    )
    ds = dataframe_to_dataset(df)
    est = JaxEstimator(
        model=MLP(), loss="mse", feature_columns=["x", "y"],
        label_column="z", batch_size=128, num_epochs=2, donate_state=False,
    )
    est.fit(ds)

    path = sys.argv[1] if len(sys.argv) > 1 else "trace_smoke.json"
    raydp_tpu.export_trace(path)
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    for event in events:
        missing = [k for k in ("ph", "ts", "pid", "tid", "name") if k not in event]
        assert not missing, f"event missing {missing}: {event}"
    complete = [e for e in events if e["ph"] == "X"]
    procs = {e["pid"] for e in complete}
    assert len(procs) >= 3, (
        f"expected spans from >=3 processes (driver, head, executor), "
        f"got {len(procs)}: {procs}"
    )
    # causal linking: executor task spans under a driver stage's trace id
    stage_traces = {
        e["args"]["trace_id"] for e in complete if e["name"] == "etl.stage"
    }
    task_traces = {
        e["args"]["trace_id"] for e in complete if e["name"] == "task.run"
    }
    assert stage_traces & task_traces, (
        f"task spans not linked to stage traces: {stage_traces} vs {task_traces}"
    )
    metrics = raydp_tpu.dump_metrics()
    assert metrics, "dump_metrics returned nothing"
    print(
        f"trace ok: {len(events)} events from {len(procs)} processes, "
        f"{len(metrics)} metric registries -> {path}"
    )


if __name__ == "__main__":
    main()
