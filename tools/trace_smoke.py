"""CI smoke: one traced ETL→fit→serve run, exported and validated as
Perfetto JSON.

Run: ``python tools/trace_smoke.py [out.json]``. Asserts the trace contains
complete spans from at least three distinct processes (driver, head, and at
least one executor actor) linked under a shared trace id — the end-to-end
guarantee the tracing plane makes — AND that one sampled SERVE request's
trace spans at least three processes under one trace id (driver request/
batch spans, the head's actor-lookup span, and the replica's compute span:
the request-path tracing contract of docs/observability.md). CI uploads the
resulting file as a build artifact so any run's timeline can be opened in
https://ui.perfetto.dev.
"""
# raydp-lint: disable-file=print-diagnostics (standalone CI tool: its stdout IS the report, there is no obs role to tag)

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("RAYDP_TPU_TRACE", "1")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np
import pandas as pd

import raydp_tpu
from raydp_tpu.estimator import JaxEstimator
from raydp_tpu.etl import functions as F
from raydp_tpu.exchange import dataframe_to_dataset


def main() -> None:
    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(1)(nn.relu(nn.Dense(16)(x)))

    session = raydp_tpu.init_etl(
        "trace-smoke", num_executors=2, executor_cores=1,
        executor_memory="300M",
    )
    rng = np.random.default_rng(0)
    pdf = pd.DataFrame(
        {
            "x": rng.random(2048).astype("float32"),
            "y": rng.random(2048).astype("float32"),
        }
    )
    df = session.from_pandas(pdf, num_partitions=4).with_column(
        "z", F.col("x") * 2 + F.col("y")
    )
    ds = dataframe_to_dataset(df)
    import tempfile

    est = JaxEstimator(
        model=MLP(), loss="mse", feature_columns=["x", "y"],
        label_column="z", batch_size=128, num_epochs=2, donate_state=False,
        checkpoint_dir=tempfile.mkdtemp(prefix="trace-smoke-ckpt-"),
    )
    est.fit(ds)

    # serve leg: a one-replica deployment with every request sampled; the
    # replica flushes its spans on a throttle, so a second wave of requests
    # after the throttle window ships the first wave's compute spans
    import time

    from raydp_tpu import serve

    x = pdf[["x", "y"]].to_numpy(np.float32)
    dep = serve.deploy(
        est, replicas=1, example=x[0],
        conf={"serve.max_batch_size": 8, "obs.request_sample_rate": 1.0},
    )
    for i in range(4):
        dep.predict(x[i : i + 1])
    time.sleep(0.7)
    dep.predict(x[0:1])
    time.sleep(0.2)
    dep.close()

    # decode leg: one streamed generation with EVERY stream sampled — the
    # stream trace must link the driver (serve.stream root), the head
    # (actor RPC), and the replica (prefill + step fan-in spans) under one
    # trace id; a second stream after the replica's flush throttle window
    # ships the first stream's engine spans (same discipline as above)
    import jax
    import jax.numpy as jnp

    from raydp_tpu.models import TransformerLM

    lm_vocab = 32
    lm = TransformerLM(
        vocab_size=lm_vocab, d_model=32, num_heads=2, num_layers=2,
        max_len=256, attn_impl="flash", dtype=jnp.float32,
    )
    lm_ckpt = tempfile.mkdtemp(prefix="trace-smoke-lm-")
    lm_est = JaxEstimator(model=lm, checkpoint_dir=lm_ckpt)
    lm_params = lm.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))
    lm_est._save_checkpoint(lm_params, 0, {})
    dep2 = serve.deploy(
        model=lm, checkpoint_dir=lm_ckpt, replicas=1,
        conf={
            "serve.decode.enabled": True,
            "serve.decode.capacity_tokens": 64,
            "serve.decode.page_tokens": 16,
            "obs.request_sample_rate": 1.0,
        },
    )
    streamed = list(dep2.stream([1, 2, 3], 8, timeout=120))
    assert streamed, "decode leg streamed no tokens"
    time.sleep(0.7)
    list(dep2.stream([2, 3, 4], 4, timeout=120))
    time.sleep(0.2)
    dep2.close()

    if len(sys.argv) > 1:
        path = sys.argv[1]
    else:
        # tool traces land in the gitignored artifacts/ dir, not the repo
        # root (obs/profiler.py artifacts_dir)
        from raydp_tpu.obs.profiler import artifacts_dir

        path = os.path.join(artifacts_dir(), "trace_smoke.json")
    raydp_tpu.export_trace(path)
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    for event in events:
        missing = [k for k in ("ph", "ts", "pid", "tid", "name") if k not in event]
        assert not missing, f"event missing {missing}: {event}"
    complete = [e for e in events if e["ph"] == "X"]
    procs = {e["pid"] for e in complete}
    assert len(procs) >= 3, (
        f"expected spans from >=3 processes (driver, head, executor), "
        f"got {len(procs)}: {procs}"
    )
    # causal linking: executor task spans under a driver stage's trace id
    stage_traces = {
        e["args"]["trace_id"] for e in complete if e["name"] == "etl.stage"
    }
    task_traces = {
        e["args"]["trace_id"] for e in complete if e["name"] == "task.run"
    }
    assert stage_traces & task_traces, (
        f"task spans not linked to stage traces: {stage_traces} vs {task_traces}"
    )
    # serve request-path linkage: at least one sampled request trace whose
    # spans come from >=3 processes (driver, head, replica) under ONE
    # trace id — the fan-in request → batch → replica-compute chain
    track_proc = {
        e["pid"]: e["args"]["name"].split(" ", 1)[0]
        for e in events if e["ph"] == "M"
    }
    request_traces = {
        e["args"]["trace_id"] for e in complete if e["name"] == "serve.request"
    }
    assert request_traces, "no sampled serve.request spans in trace"
    best_procs: set = set()
    for trace_id in request_traces:
        procs_in_trace = {
            track_proc.get(e["pid"], str(e["pid"]))
            for e in complete if e["args"].get("trace_id") == trace_id
        }
        if len(procs_in_trace) > len(best_procs):
            best_procs = procs_in_trace
    assert len(best_procs) >= 3, (
        f"serve request trace spans only {best_procs} — expected >=3 "
        "processes (driver, head, replica) under one trace id"
    )
    batch_spans = [e for e in complete if e["name"] == "serve.batch"]
    infer_spans = [e for e in complete if e["name"] == "serve.replica_infer"]
    assert batch_spans and infer_spans, (
        f"missing serve fan-in spans: {len(batch_spans)} batch, "
        f"{len(infer_spans)} replica_infer"
    )
    # decode stream-path linkage: at least one sampled stream trace whose
    # spans come from >=3 processes under ONE trace id, carrying the
    # replica's prefill span and >=1 decode-step fan-in span listing the
    # streams that rode that batch round
    stream_spans = [e for e in complete if e["name"] == "serve.stream"]
    assert stream_spans, "no sampled serve.stream spans in trace"
    stream_trace = None
    stream_procs: set = set()
    for event in stream_spans:
        trace_id = event["args"].get("trace_id")
        procs_in_trace = {
            track_proc.get(e["pid"], str(e["pid"]))
            for e in complete if e["args"].get("trace_id") == trace_id
        }
        if len(procs_in_trace) > len(stream_procs):
            stream_procs, stream_trace = procs_in_trace, trace_id
    assert len(stream_procs) >= 3, (
        f"decode stream trace spans only {stream_procs} — expected >=3 "
        "processes (driver, head, replica) under one trace id"
    )
    prefill_spans = [
        e for e in complete if e["name"] == "serve.decode.prefill"
        and e["args"].get("trace_id") == stream_trace
    ]
    step_spans = [
        e for e in complete if e["name"] == "serve.decode.step"
        and e["args"].get("trace_id") == stream_trace
    ]
    assert prefill_spans, "no serve.decode.prefill span on the stream trace"
    assert step_spans and any(
        e["args"].get("stream_spans") for e in step_spans
    ), (
        f"missing decode-step fan-in spans on the stream trace: "
        f"{len(step_spans)} steps"
    )
    metrics = raydp_tpu.dump_metrics()
    assert metrics, "dump_metrics returned nothing"
    print(
        f"trace ok: {len(events)} events from {len(procs)} processes, "
        f"serve request trace across {len(best_procs)} processes, "
        f"decode stream trace across {len(stream_procs)} processes "
        f"({len(prefill_spans)} prefill + {len(step_spans)} step spans), "
        f"{len(metrics)} metric registries -> {path}"
    )


if __name__ == "__main__":
    main()
