"""CI perf-smoke: run the NYCTaxi + streaming bench slices on CPU and gate
gross ETL regressions.

Runs ``bench.py`` with small row counts (CI-sized; override via the usual
BENCH_* env vars), writes an artifact JSON holding the headline ETL numbers
plus the full ``etl_breakdown`` and per-exchange shuffle stats, and FAILS
when:

- ``etl_query_s`` regresses beyond the sentry ledger's baseline + noise
  band (``BENCH_BASELINE.json``, built by ``tools/perf_sentry.py`` from
  every committed ``BENCH_r*`` snapshot — per-stat noise bands replace the
  old hand-pinned r08 constants; the r08 snapshot + flat 25% budget remains
  the fallback on a checkout without the ledger). The CI slice runs ~10x
  fewer rows than the snapshot's run, so this is a smoke gate for gross
  regressions — a structural slowdown in the data plane, not a ±10% noise
  detector;
- the interactive-burst p50 (``burst_p50_ms``) regresses beyond its ledger
  baseline + band — the millisecond-control-plane gate (plan cache +
  run_plan dispatch + head bypass + doorbell all sit under this number);
- the burst's repeated-query slice shows NO plan-cache hits (hit-rate must
  be > 0: identical query shapes re-executed must not replan);
- an indexed shuffle writes more blocks than map tasks (the M-not-M×R
  invariant of the pipelined shuffle data plane);
- the uncached streaming fit's ``consumer_idle_s`` exceeds 0.2s — the
  device-speed-ingest gate: the whole-fit producer + N-way upload streams
  must keep the consumer thread fed (a per-epoch pipeline restart or a
  decode moved back onto the consumer thread shows up here first);
- the hybrid/streaming quotient (``streaming_hybrid_vs_scan`` over
  ``streaming_vs_scan``, both interleaved medians since r07) falls more
  than 25% below the snapshot's quotient. The quotient — not the raw
  hybrid ratio — is what transfers across scales: the CI slice's tiny
  fits are dispatch/compile-dominated, which deflates BOTH ratios
  against the snapshot's 10x-bigger run, while "hybrid regressed below
  the uncached path" (the r06 symptom this gate exists for) shows up in
  the quotient at any scale;
- the recovery probe failed (``recovery_probe.ok`` false): BOTH ownership
  tiers must hold — with the block service ON an injected executor SIGKILL
  must come back correct with ZERO re-executed tasks (executor death loses
  no blocks), and with the service deregistered the same kill must recover
  through lineage with ≥1 re-executed task. ``recovery_overhead`` itself is
  reported, not gated — but the etl_query_s/burst gates above hold the
  CLEAN path to <25% regression vs the r08 snapshot, i.e. the block-service
  handoff (like the lineage bookkeeping before it) must be ~free;
- the serving probe's closed-loop p99 exceeds its fixed SLO
  (``BENCH_SERVE_SLO_MS``, 250ms — an absolute smoke budget like the
  consumer-idle gate: generous vs the ~7ms measured on a 2-core box, it
  catches structural request-path regressions such as a per-request
  compile or a fresh connect per dispatch);
- the serving kill-during-load probe failed zero-drop recovery: a replica
  SIGKILL mid-stream must drop ZERO requests, return responses
  byte-identical to an unkilled run, and the pool must heal to target
  (docs/serving.md "Failover");
- the tenant-isolation probe failed (docs/multitenancy.md): with a
  co-tenant churning a heavy shuffle on the same cluster, the interactive
  tenant's burst p99 must stay within 3x of its solo baseline, and at
  least one cross-tenant plan-cache hit must be recorded (identical query
  shapes from different tenants share one compiled program);
- telemetry overhead exceeds 5% on the warm compiled-query p50
  (``obs_overhead_probe``: interleaved medians of span-shipping-on vs -off
  bursts, plus a 0.25 ms absolute floor so timer quantization on a sub-ms
  p50 cannot fail the gate on a noisy 2-core box) — the always-on
  telemetry plane must stay ~free on the hot path;
- the Prometheus scrape-endpoint liveness check failed: one real scrape of
  the head's ``obs.scrape_port`` endpoint must parse in the exposition
  format, carry at least one ``tenant``-labeled series, and at least one
  ``serve_`` series (docs/observability.md "Scrape endpoint");
- step-profiler overhead exceeds 5% on the fit step p50
  (``fit_profile_probe``: interleaved medians of profiler-on vs -off fits,
  +0.25 ms quantization floor — the always-on step-phase decomposition
  must stay ~free on the train loop);
- the live-MFU parity check failed: the estimator's live FLOPs accounting
  (XLA cost analysis, the ``estimator.mfu`` gauge) and the cost-model's
  analytic FLOPs for the same model must agree within the probe's
  tolerance (docs/observability.md "Compute observatory");
- the cross-host probe failed (``crosshost_shuffle_probe``,
  docs/cluster.md "Multi-host topology"): the simulated 2-host shuffle +
  fit must be byte-identical to the single-host arm, the remote arm must
  move > 0 bytes over the wire (``rpc.bytes_over_wire``), and the reduce
  placement locality hit rate (``planner.locality_hits / (hits+misses)``)
  must be ≥ 0.8.

Usage: ``python tools/perf_smoke.py [artifact.json]``
"""
# raydp-lint: disable-file=print-diagnostics (standalone CI tool: its stdout IS the report, there is no obs role to tag)

from __future__ import annotations

import json
import os
import re
import subprocess
import sys

REGRESSION_BUDGET = 0.25  # fallback budget when the sentry ledger is absent
CONSUMER_IDLE_BUDGET_S = 0.2  # absolute: the streaming consumer stays fed
OBS_OVERHEAD_BUDGET = 0.05  # telemetry-on vs -off on the warm-query p50
PROFILER_OVERHEAD_BUDGET = 0.05  # step-profiler-on vs -off on the fit step p50

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# legacy fallback snapshot — thresholds normally come from the sentry's
# committed BENCH_BASELINE.json (tools/perf_sentry.py); this keeps the
# tool runnable on a checkout without the ledger
SNAPSHOT = "BENCH_r08.json"


def _sentry_baseline() -> dict:
    """The committed sentry ledger's baseline section ({} when absent or
    invalid — callers fall back to the r08 snapshot constants)."""
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    try:
        from tools.perf_sentry import load_baseline

        return load_baseline() or {}
    except Exception:
        return {}


def _snapshot_value(key: str) -> float | None:
    """A headline number from the committed bench snapshot (the snapshot
    stores the bench stdout tail; first occurrence is the NYCTaxi slice)."""
    path = os.path.join(REPO, SNAPSHOT)
    try:
        with open(path) as f:
            tail = json.load(f).get("tail", "")
    except (OSError, ValueError):
        return None
    found = re.search(rf'"{key}": ([0-9.]+)', tail)
    return float(found.group(1)) if found else None


def snapshot_etl_query_s() -> float | None:
    return _snapshot_value("etl_query_s")


def run_bench() -> dict:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("BENCH_ROWS", "20000")
    env.setdefault("BENCH_DLRM_ROWS", "10000")
    env.setdefault("BENCH_SAMPLES", "1")
    env.setdefault("BENCH_EPOCHS", "4")
    env.setdefault("BENCH_DLRM_EPOCHS", "4")
    env.setdefault("BENCH_BURST", "200")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=1800, env=env, cwd=REPO,
    )
    if out.returncode != 0:
        sys.stderr.write(out.stderr[-2000:])
        raise SystemExit(f"bench.py failed rc={out.returncode}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> int:
    artifact_path = sys.argv[1] if len(sys.argv) > 1 else "perf_smoke.json"
    result = run_bench()
    detail = result["detail"]
    baseline = _sentry_baseline()

    def ref_of(stat: str, legacy_key: str):
        """(reference value, regression budget) for one gated stat: the
        sentry ledger's baseline + noise band when committed, else the
        legacy r08-snapshot value + the flat 25% budget."""
        entry = baseline.get(stat)
        if entry and entry.get("value"):
            return float(entry["value"]), float(entry["band"])
        return _snapshot_value(legacy_key), REGRESSION_BUDGET

    reference, etl_budget = ref_of("etl_query_s", "etl_query_s")
    burst_ref, burst_budget = ref_of("burst_p50_ms", "burst_p50_ms")
    artifact = {
        "thresholds_source": (
            "sentry-ledger" if baseline else "r08-snapshot"
        ),
        "etl_query_s": detail["etl_query_s"],
        "burst_p50_ms": detail.get("burst_p50_ms"),
        "burst_p99_ms": detail.get("burst_p99_ms"),
        "plan_cache_hit_rate": detail.get("plan_cache_hit_rate"),
        "burst_last_query": detail.get("burst_last_query", {}),
        "pandas_etl_s": detail["pandas_etl_s"],
        "cluster_boot_s": detail["cluster_boot_s"],
        "streaming_vs_scan": detail["streaming_vs_scan"],
        "streaming_hybrid_vs_scan": detail.get("streaming_hybrid_vs_scan"),
        "streaming_pipeline": detail.get("streaming_pipeline", {}),
        "streaming_hybrid_pipeline": detail.get(
            "streaming_hybrid_pipeline", {}
        ),
        "streaming_ingest_probe": detail.get("streaming_ingest_probe", {}),
        "recovery_probe": detail.get("recovery_probe", {}),
        "serving_probe": detail.get("serving_probe", {}),
        "decode_serving_probe": detail.get("decode_serving_probe", {}),
        "decode_obs_probe": detail.get("decode_obs_probe", {}),
        "tenant_isolation_probe": detail.get("tenant_isolation_probe", {}),
        "obs_overhead_probe": detail.get("obs_overhead_probe", {}),
        "recovery_overhead": detail.get("recovery_overhead"),
        "etl_breakdown": detail.get("etl_breakdown", {}),
        "shuffle_probe": detail.get("shuffle_probe", {}),
        "fit_profile_probe": detail.get("fit_profile_probe", {}),
        "crosshost_shuffle_probe": detail.get("crosshost_shuffle_probe", {}),
        "reference_etl_query_s": reference,
        "reference_burst_p50_ms": burst_ref,
        "reference_streaming_vs_scan": _snapshot_value("streaming_vs_scan"),
        "reference_streaming_hybrid_vs_scan": _snapshot_value(
            "streaming_hybrid_vs_scan"
        ),
        "regression_budget": REGRESSION_BUDGET,
        "rows": detail.get("rows"),
    }
    with open(artifact_path, "w") as f:
        json.dump(artifact, f, indent=2)
    print(json.dumps(artifact, indent=2))

    failures = []
    if reference is not None:
        limit = reference * (1.0 + etl_budget)
        if detail["etl_query_s"] > limit:
            failures.append(
                f"etl_query_s {detail['etl_query_s']:.3f}s exceeds "
                f"{limit:.3f}s ({artifact['thresholds_source']} "
                f"{reference:.3f}s + {etl_budget:.0%})"
            )
    burst_p50 = artifact["burst_p50_ms"]
    if burst_ref is not None and burst_p50 is not None:
        limit = burst_ref * (1.0 + burst_budget)
        if burst_p50 > limit:
            failures.append(
                f"burst_p50_ms {burst_p50:.2f} exceeds {limit:.2f} "
                f"({artifact['thresholds_source']} {burst_ref:.2f} + "
                f"{burst_budget:.0%})"
            )
    hit_rate = artifact["plan_cache_hit_rate"]
    if hit_rate is not None and hit_rate <= 0.0:
        failures.append(
            "plan-cache hit-rate is 0 on the repeated-query burst slice "
            "(identical query shapes re-executed must not replan)"
        )
    consumer_idle = artifact["streaming_pipeline"].get("consumer_idle_s")
    if consumer_idle is not None and consumer_idle > CONSUMER_IDLE_BUDGET_S:
        failures.append(
            f"streaming consumer_idle_s {consumer_idle:.3f}s exceeds the "
            f"{CONSUMER_IDLE_BUDGET_S:.1f}s budget (uncached streaming must "
            "keep the consumer thread fed — whole-fit producer / N-way "
            "upload streams / off-thread decode)"
        )
    hybrid_ref = artifact["reference_streaming_hybrid_vs_scan"]
    streaming_ref = artifact["reference_streaming_vs_scan"]
    hybrid_ratio = artifact["streaming_hybrid_vs_scan"]
    streaming_ratio = artifact["streaming_vs_scan"]
    if None not in (hybrid_ref, streaming_ref, hybrid_ratio, streaming_ratio) \
            and streaming_ref > 0 and streaming_ratio > 0:
        quotient = hybrid_ratio / streaming_ratio
        quotient_ref = hybrid_ref / streaming_ref
        floor = quotient_ref * (1.0 - REGRESSION_BUDGET)
        if quotient < floor:
            failures.append(
                f"hybrid/streaming quotient {quotient:.4f} below "
                f"{floor:.4f} (snapshot {quotient_ref:.4f} - "
                f"{REGRESSION_BUDGET:.0%}: hybrid regressed vs the "
                "uncached path)"
            )
    recovery = artifact["recovery_probe"]
    if recovery and not recovery.get("ok"):
        failures.append(
            f"recovery probe failed: {recovery} (service ON: an injected "
            "executor SIGKILL must be loss-free with 0 re-executed tasks; "
            "service OFF: the same kill must recover byte-correct via "
            "lineage with ≥1 re-executed task)"
        )
    serving = artifact["serving_probe"]
    if serving:
        slo = serving.get("slo_ms")
        p99 = serving.get("p99_ms")
        if p99 is None or (slo is not None and p99 > slo):
            failures.append(
                f"serving p99 {p99}ms exceeds the {slo}ms SLO budget "
                "(closed-loop probe: a structural request-path regression — "
                "per-request compile, fresh connects, batcher stall)"
            )
        kill = serving.get("kill_probe", {})
        if not kill.get("ok"):
            failures.append(
                f"serving kill-during-load probe failed: {kill} (a replica "
                "SIGKILL mid-stream must drop zero requests, stay "
                "byte-identical to an unkilled run, and heal the pool)"
            )
    else:
        failures.append("serving_probe missing from bench detail")
    decode = artifact["decode_serving_probe"]
    if decode:
        parity = decode.get("kernel_parity", {})
        if not parity.get("ok"):
            failures.append(
                f"decode kernel parity failed: {parity} (the one-pass "
                "body and the decode step must stay BIT-identical to "
                "their references — any drift breaks the failover "
                "re-prefill determinism contract)"
            )
        token_p99 = decode.get("token_p99_ms")
        token_slo = decode.get("token_slo_ms")
        if token_p99 is None or (
            token_slo is not None and token_p99 > token_slo
        ):
            failures.append(
                f"decode per-token p99 {token_p99}ms exceeds the "
                f"{token_slo}ms SLO budget (streaming probe: a structural "
                "decode-loop regression — compile inside the step, "
                "scheduler stall, poll-path stall)"
            )
        tps = decode.get("decode_tokens_per_sec")
        tps_entry = _sentry_baseline().get("decode_tokens_per_sec")
        if tps is None or tps <= 0:
            failures.append(
                f"decode_tokens_per_sec missing or zero: {decode}"
            )
        elif tps_entry and tps_entry.get("value"):
            floor = float(tps_entry["value"]) * (
                1.0 - float(tps_entry["band"])
            )
            if tps < floor:
                failures.append(
                    f"decode_tokens_per_sec {tps:.1f} below the sentry "
                    f"floor {floor:.1f} (baseline "
                    f"{tps_entry['value']:.1f} - {tps_entry['band']:.0%})"
                )
        if not decode.get("ok"):
            failures.append(f"decode serving probe failed: {decode}")
    else:
        failures.append("decode_serving_probe missing from bench detail")
    decode_obs = artifact["decode_obs_probe"]
    if decode_obs:
        if not decode_obs.get("ok"):
            failures.append(f"decode obs overhead probe failed: {decode_obs}")
        else:
            token_on = decode_obs.get("token_ms_on")
            token_off = decode_obs.get("token_ms_off")
            if token_on is None or token_off is None:
                failures.append(
                    f"decode obs overhead probe incomplete: {decode_obs}"
                )
            # same shape as the telemetry/profiler gates: ≤5% on the
            # per-token p50 with a 0.25 ms quantization floor — stream
            # tracing at sample rate 1.0 must stay ~free per decoded token
            elif token_on > token_off * (1.0 + OBS_OVERHEAD_BUDGET) + 0.25:
                failures.append(
                    f"decode tracing-on token p50 {token_on:.3f}ms exceeds "
                    f"tracing-off {token_off:.3f}ms by more than "
                    f"{OBS_OVERHEAD_BUDGET:.0%} (+0.25ms floor): the decode "
                    "observatory must stay ~free per decoded token"
                )
    else:
        failures.append("decode_obs_probe missing from bench detail")
    tenant = artifact["tenant_isolation_probe"]
    if tenant:
        ratio = tenant.get("p99_ratio")
        if ratio is None or ratio > 3.0:
            failures.append(
                f"tenant-isolation p99 ratio {ratio} exceeds 3.0x (a noisy "
                "co-tenant's shuffle moved the interactive tenant's p99 "
                "beyond the bounded-interference budget)"
            )
        if int(tenant.get("cross_tenant_hits", 0)) < 1:
            failures.append(
                "no cross-tenant plan-cache hit recorded (identical query "
                "shapes from different tenants must share one compiled "
                "program)"
            )
        if not tenant.get("ok"):
            failures.append(f"tenant-isolation probe failed: {tenant}")
    else:
        failures.append("tenant_isolation_probe missing from bench detail")
    obs_probe = artifact["obs_overhead_probe"]
    if obs_probe:
        on_ms = obs_probe.get("p50_on_ms")
        off_ms = obs_probe.get("p50_off_ms")
        if on_ms is None or off_ms is None:
            failures.append(f"obs overhead probe incomplete: {obs_probe}")
        # ≤5% on the warm p50, with a 0.25 ms absolute floor: at sub-ms
        # p50s a single timer-quantization step would otherwise read as
        # >5% — the floor keeps the gate meaningful, not flaky
        elif on_ms > off_ms * (1.0 + OBS_OVERHEAD_BUDGET) + 0.25:
            failures.append(
                f"telemetry-on p50 {on_ms:.3f}ms exceeds telemetry-off "
                f"{off_ms:.3f}ms by more than {OBS_OVERHEAD_BUDGET:.0%} "
                "(+0.25ms floor): the always-on telemetry plane must stay "
                "~free on the warm query path"
            )
        scrape_check = obs_probe.get("scrape", {})
        if not scrape_check.get("ok"):
            failures.append(
                f"scrape-endpoint liveness failed: {scrape_check} (one "
                "scrape of obs.scrape_port must parse)"
            )
        else:
            if not scrape_check.get("has_tenant_label"):
                failures.append(
                    "scrape carries no tenant-labeled series (per-tenant "
                    "labels are the multi-tenant observability contract)"
                )
            if not scrape_check.get("has_serve_series"):
                failures.append(
                    "scrape carries no serve_ series (the serving plane's "
                    "gauges must reach the head TSDB)"
                )
    else:
        failures.append("obs_overhead_probe missing from bench detail")
    fit_probe = artifact["fit_profile_probe"]
    if fit_probe:
        on_ms = fit_probe.get("step_p50_on_ms")
        off_ms = fit_probe.get("step_p50_off_ms")
        if on_ms is None or off_ms is None:
            failures.append(f"fit profile probe incomplete: {fit_probe}")
        # same shape as the telemetry gate: ≤5% on the fit step p50 with a
        # 0.25 ms quantization floor — the ALWAYS-ON step profiler must
        # stay ~free on the train loop
        elif on_ms > off_ms * (1.0 + PROFILER_OVERHEAD_BUDGET) + 0.25:
            failures.append(
                f"step-profiler-on fit step p50 {on_ms:.3f}ms exceeds "
                f"profiler-off {off_ms:.3f}ms by more than "
                f"{PROFILER_OVERHEAD_BUDGET:.0%} (+0.25ms floor)"
            )
        if not fit_probe.get("mfu_parity_ok"):
            failures.append(
                f"live-MFU vs bench-analytic parity failed: {fit_probe} "
                "(the estimator's XLA-cost-analysis FLOPs and the "
                "costmodel's analytic FLOPs must describe the same step)"
            )
    else:
        failures.append("fit_profile_probe missing from bench detail")
    for entry in artifact["shuffle_probe"].get("shuffle", []):
        if entry.get("indexed") and entry["blocks"] > entry["map_tasks"]:
            failures.append(
                f"indexed shuffle wrote {entry['blocks']} blocks for "
                f"{entry['map_tasks']} map tasks (expected M, not M×R)"
            )
    xhost = artifact["crosshost_shuffle_probe"]
    if xhost:
        if not (xhost.get("parity_ok") and xhost.get("fit_parity_ok")):
            failures.append(
                f"cross-host parity failed: {xhost} (the simulated 2-host "
                "shuffle + fit must be byte-identical to single-host)"
            )
        rate = xhost.get("locality_hit_rate")
        if rate is None or rate < 0.8:
            failures.append(
                f"cross-host locality hit rate {rate} below 0.8 (reduce "
                "placement must follow the input bytes on a multi-host "
                "pool)"
            )
        if int(xhost.get("bytes_over_wire", 0)) <= 0:
            failures.append(
                "cross-host probe moved zero bytes over the wire (the "
                "remote arm never exercised the cross-host data plane)"
            )
    else:
        failures.append("crosshost_shuffle_probe missing from bench detail")
    if failures:
        for f_ in failures:
            print(f"PERF-SMOKE FAIL: {f_}", file=sys.stderr)
        return 1
    print("PERF-SMOKE OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
