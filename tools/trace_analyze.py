"""Critical-path attribution over an exported Perfetto trace.

Run: ``python tools/trace_analyze.py trace.json [--root etl.query]
[--trace <trace-id>] [--top 5] [--json report.json]``

Loads a ``raydp_tpu.export_trace`` JSON, reconstructs the span graph from
the event args (``trace_id`` / ``span_id`` / ``parent_id`` ride every
exported event), picks the root span (``--root`` name, ``--trace`` id, or
the longest parentless span), and prints the ``obs/analysis.py`` wall-time
attribution: per-category critical-path totals plus the top-K widest
stalls. This is the tool perf work cites instead of eyeballing the
timeline — "the query is 40% dispatch, and the widest stall is 3.1 ms in
etl.stage after task.run" is an actionable sentence; a screenshot is not.
"""
# raydp-lint: disable-file=print-diagnostics (standalone CLI tool: its stdout IS the report, there is no obs role to tag)

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def records_from_trace(doc: dict) -> List[dict]:
    """Perfetto trace events → the span-record shape ``obs/analysis.py``
    consumes. Metadata events name the process tracks; complete events
    carry ids in args."""
    track_names = {}
    for event in doc.get("traceEvents", []):
        if event.get("ph") == "M" and event.get("name") == "process_name":
            track_names[event.get("pid")] = (
                (event.get("args") or {}).get("name", "proc")
            )
    records = []
    for event in doc.get("traceEvents", []):
        if event.get("ph") not in ("X", "i"):
            continue
        args = dict(event.get("args") or {})
        records.append({
            "name": event.get("name", "span"),
            "ts": int(event.get("ts", 0)),
            "dur": int(event.get("dur", 0)),
            "ph": event.get("ph") if event.get("ph") == "i" else None,
            "proc": track_names.get(event.get("pid"), str(event.get("pid"))),
            "trace": args.pop("trace_id", None),
            "id": args.pop("span_id", None),
            "parent": args.pop("parent_id", None),
            "args": args,
        })
    return records


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("trace", help="export_trace JSON path")
    parser.add_argument("--root", default=None,
                        help="root span NAME (e.g. etl.query, serve.request);"
                             " default: longest parentless span")
    parser.add_argument("--trace-id", default=None,
                        help="restrict root selection to one trace id")
    parser.add_argument("--top", type=int, default=5,
                        help="widest stalls to report")
    parser.add_argument("--json", default=None,
                        help="also write the report as JSON here")
    args = parser.parse_args(argv)

    from raydp_tpu.obs.analysis import attribute, format_report

    with open(args.trace) as f:
        doc = json.load(f)
    records = records_from_trace(doc)
    if not records:
        print("no span events in trace", file=sys.stderr)
        return 1
    try:
        report = attribute(records, root_name=args.root,
                           trace=args.trace_id, top_k=args.top)
    except ValueError as exc:
        print(f"trace_analyze: {exc}", file=sys.stderr)
        return 1
    print(format_report(report))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2)
        print(f"report -> {args.json}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
